"""RTMP protocol: handshake, chunk stream, NetConnection/NetStream
commands, and a live publish/play relay server (compact re-design of the
reference's media stack: rtmp.{h,cpp} 2885 LoC — RtmpClient rtmp.h:723,
RtmpStreamBase rtmp.h:518 — and policy/rtmp_protocol.cpp 3677 LoC).

Covered: C0C1C2/S0S1S2 handshake in BOTH flavors — the plain echo and
the digest ("complex") handshake stock encoders perform, schemes 0 and
1, with keyed S2/C2 acks (see the digest-handshake section below);
chunk basic/message headers fmt0-3 with extended timestamps and
SET_CHUNK_SIZE on both directions; control messages (ack window, peer
bw, user control); AMF0 command messages (connect, createStream,
publish, play, deleteStream, onStatus, _result) plus the AMF3 command
envelope (type 17, with amf.py's AMF3 read side for objectEncoding-3
peers); aggregate messages (type 22) split into their sub-messages with
rebased timestamps; audio/video/data relay with sequence-header +
metadata caching for late-joining players. Out of scope: HLS remux
(see flv.py for the FLV side) and RTMPE/RTMPS encryption."""

from __future__ import annotations

import inspect
import os
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.protocol import amf
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import create_client_socket

RTMP_VERSION = 3
HANDSHAKE_SIZE = 1536
DEFAULT_IN_CHUNK = 128
OUT_CHUNK_SIZE = 4096
_MAX_MSG = 32 << 20

# message type ids
MSG_SET_CHUNK_SIZE = 1
MSG_ABORT = 2
MSG_ACK = 3
MSG_USER_CONTROL = 4
MSG_WINDOW_ACK_SIZE = 5
MSG_SET_PEER_BW = 6
MSG_AUDIO = 8
MSG_VIDEO = 9
MSG_DATA_AMF3 = 15
MSG_COMMAND_AMF3 = 17
MSG_DATA_AMF0 = 18
MSG_COMMAND_AMF0 = 20
MSG_AGGREGATE = 22

_CONTROL_CSID = 2
_COMMAND_CSID = 3
_MEDIA_CSID = 6


# ------------------------------------------------------ digest handshake
# The "complex" handshake stock encoders perform (the reference's
# handshake schemes in policy/rtmp_protocol.cpp; the key material and
# HMAC layout are public normative constants from the Flash ecosystem,
# same family as nginx-rtmp/librtmp/ffmpeg): C1/S1 carry an
# HMAC-SHA256 digest embedded at an offset derived from 4 offset bytes,
# in one of two schemes (offset block right after the version word, or
# after a 764-byte key block); C2/S2 are random blocks whose last 32
# bytes are keyed on the peer's digest. A C1 with a zero version word
# is the plain echo handshake.
#
# Degradation is graceful in BOTH directions by construction: a digest
# the server can't validate (unknown scheme, or key-constant drift)
# falls back to the plain echo with a zero-version S1, which stock
# encoders accept as a simple-handshake server; a client whose S1 shows
# no server digest echoes S1 as plain C2. So a wrong key constant
# degrades to the plain handshake instead of breaking connections.
_FP_KEY = b"Genuine Adobe Flash Player 001"          # client partial (30)
_FMS_KEY = b"Genuine Adobe Flash Media Server 001"   # server partial (36)
_KEY_TAIL = bytes((0xF0, 0xEE, 0xC2, 0x4A, 0x80, 0x68, 0xBE, 0xE8,
                   0x2E, 0x00, 0xD0, 0xD1, 0x02, 0x9E, 0x7E, 0x57,
                   0x6E, 0xEC, 0x5D, 0x2D, 0x29, 0x80, 0x6F, 0xAB,
                   0x93, 0xB8, 0xE6, 0x36, 0xCF, 0xEB, 0x31, 0xAE))


def _hs_digest_pos(buf: bytes, scheme: int) -> int:
    base = 8 if scheme == 0 else 772
    return base + 4 + sum(buf[base:base + 4]) % 728


def _hs_make_digest(buf: bytes, pos: int, key: bytes) -> bytes:
    import hashlib
    import hmac as _hmac
    return _hmac.new(key, buf[:pos] + buf[pos + 32:],
                     hashlib.sha256).digest()


def _hs_find_digest(block: bytes, key: bytes):
    """(scheme, digest) when the 1536-byte block carries a valid digest
    under ``key``; None for the plain handshake."""
    for scheme in (0, 1):
        pos = _hs_digest_pos(block, scheme)
        if pos + 32 <= len(block) and \
                block[pos:pos + 32] == _hs_make_digest(block, pos, key):
            return scheme, block[pos:pos + 32]
    return None


def _hs_build_block(key: bytes, scheme: int, version: bytes) -> Tuple[bytes, bytes]:
    """A 1536-byte C1/S1 with an embedded digest; returns (block, digest)."""
    buf = bytearray(os.urandom(HANDSHAKE_SIZE))
    buf[0:4] = b"\x00\x00\x00\x00"
    buf[4:8] = version
    pos = _hs_digest_pos(buf, scheme)
    dig = _hs_make_digest(bytes(buf), pos, key)
    buf[pos:pos + 32] = dig
    return bytes(buf), dig


def _hs_ack_block(peer_digest: bytes, full_key: bytes) -> bytes:
    """A C2/S2 for the digest handshake: random + HMAC keyed on the
    peer's digest under the full (partial+tail) key."""
    import hashlib
    import hmac as _hmac
    rand = os.urandom(HANDSHAKE_SIZE - 32)
    tmp = _hmac.new(full_key, peer_digest, hashlib.sha256).digest()
    return rand + _hmac.new(tmp, rand, hashlib.sha256).digest()


class RtmpMessage:
    __slots__ = ("msg_type", "timestamp", "stream_id", "payload")

    def __init__(self, msg_type: int, timestamp: int, stream_id: int,
                 payload: bytes):
        self.msg_type = msg_type
        self.timestamp = timestamp
        self.stream_id = stream_id
        self.payload = payload

    def __repr__(self):
        return (f"RtmpMessage(type={self.msg_type}, ts={self.timestamp}, "
                f"sid={self.stream_id}, {len(self.payload)}B)")


class RtmpError(Exception):
    pass


# ------------------------------------------------------------ chunk writer

def pack_chunks(msg: RtmpMessage, csid: int,
                chunk_size: int = OUT_CHUNK_SIZE) -> bytes:
    """fmt0 first chunk + fmt3 continuations (always-absolute headers:
    simple, spec-correct, marginally less compact than delta encoding)."""
    ts = msg.timestamp & 0xFFFFFFFF
    ext = ts >= 0xFFFFFF
    hdr_ts = 0xFFFFFF if ext else ts
    out = []
    first = bytes([(0 << 6) | csid]) + \
        struct.pack(">I", hdr_ts)[1:] + \
        struct.pack(">I", len(msg.payload))[1:] + \
        bytes([msg.msg_type]) + struct.pack("<I", msg.stream_id)
    if ext:
        first += struct.pack(">I", ts)
    out.append(first)
    out.append(msg.payload[:chunk_size])
    pos = chunk_size
    cont = bytes([(3 << 6) | csid])
    cont_ext = struct.pack(">I", ts) if ext else b""
    while pos < len(msg.payload):
        out.append(cont)
        out.append(cont_ext)   # ext timestamp repeats on every chunk
        out.append(msg.payload[pos:pos + chunk_size])
        pos += chunk_size
    return b"".join(out)


# ------------------------------------------------------------ chunk reader

class _CsidState:
    __slots__ = ("msg_len", "msg_type", "stream_id", "timestamp", "ts_delta",
                 "buf", "has_ext")

    def __init__(self):
        self.msg_len = 0
        self.msg_type = 0
        self.stream_id = 0
        self.timestamp = 0
        self.ts_delta = 0
        self.buf = b""
        self.has_ext = False


class _ConnState:
    """Per-connection RTMP state living in socket.user_data."""

    PHASE_UNINIT = 0         # server: waiting C0C1; client: waiting S0S1S2
    PHASE_ACK = 1            # server: waiting C2;   client: (skipped)
    PHASE_READY = 2

    def __init__(self, is_client: bool):
        self.is_client = is_client
        self.phase = self.PHASE_UNINIT
        self.in_chunk_size = DEFAULT_IN_CHUNK
        self.csids: Dict[int, _CsidState] = {}
        self.next_stream_id = 1
        self.streams: Dict[int, str] = {}     # msg stream id -> role tag
        self.app = ""


def _parse_one_chunk(state: _ConnState, data: bytes, pos: int
                     ) -> Optional[Tuple[Optional[RtmpMessage], int]]:
    """One chunk at ``pos``: returns (completed_message_or_None, new_pos)
    or None if more bytes are needed. Raises RtmpError on corruption."""
    if pos >= len(data):
        return None
    b0 = data[pos]
    fmt = b0 >> 6
    csid = b0 & 0x3F
    pos += 1
    if csid == 0:
        if pos >= len(data):
            return None
        csid = 64 + data[pos]
        pos += 1
    elif csid == 1:
        if pos + 2 > len(data):
            return None
        csid = 64 + data[pos] + data[pos + 1] * 256
        pos += 2
    st = state.csids.get(csid)
    if st is None:
        if fmt != 0:
            raise RtmpError(f"first chunk on csid {csid} must be fmt0")
        st = state.csids[csid] = _CsidState()
    hdr_len = (11, 7, 3, 0)[fmt]
    if pos + hdr_len > len(data):
        return None
    # COMPUTE phase — locals only. st is committed at the very end: a
    # partial chunk (payload split across reads) returns bare None and the
    # SAME header bytes will be re-parsed next call; mutating st here
    # would apply timestamp deltas twice (real encoders use fmt1/2).
    msg_len, msg_type, stream_id = st.msg_len, st.msg_type, st.stream_id
    timestamp, ts_delta, has_ext = st.timestamp, st.ts_delta, st.has_ext
    if fmt == 0:
        ts = int.from_bytes(data[pos:pos + 3], "big")
        msg_len = int.from_bytes(data[pos + 3:pos + 6], "big")
        msg_type = data[pos + 6]
        stream_id = struct.unpack_from("<I", data, pos + 7)[0]
        has_ext = ts == 0xFFFFFF
        pos += 11
        if has_ext:
            if pos + 4 > len(data):
                return None
            ts = struct.unpack_from(">I", data, pos)[0]
            pos += 4
        timestamp = ts
        ts_delta = 0
    elif fmt in (1, 2):
        delta = int.from_bytes(data[pos:pos + 3], "big")
        if fmt == 1:
            msg_len = int.from_bytes(data[pos + 3:pos + 6], "big")
            msg_type = data[pos + 6]
        pos += hdr_len
        has_ext = delta == 0xFFFFFF
        if has_ext:
            if pos + 4 > len(data):
                return None
            delta = struct.unpack_from(">I", data, pos)[0]
            pos += 4
        ts_delta = delta
        if not st.buf:      # deltas apply at message starts only
            timestamp = (timestamp + delta) & 0xFFFFFFFF
    else:  # fmt 3: continuation (or repeat of previous header)
        if has_ext:
            if pos + 4 > len(data):
                return None
            pos += 4        # repeated extended timestamp
        if not st.buf and msg_len == 0:
            raise RtmpError(f"fmt3 chunk with no prior header on csid {csid}")
        if not st.buf:
            timestamp = (timestamp + ts_delta) & 0xFFFFFFFF
    if msg_len > _MAX_MSG:
        raise RtmpError(f"rtmp message of {msg_len} bytes exceeds max")
    take = min(state.in_chunk_size, msg_len - len(st.buf))
    if take < 0:
        raise RtmpError("chunk overrun")
    if pos + take > len(data):
        return None
    # COMMIT phase — the whole chunk is present, mutate exactly once
    st.msg_len, st.msg_type, st.stream_id = msg_len, msg_type, stream_id
    st.timestamp, st.ts_delta, st.has_ext = timestamp, ts_delta, has_ext
    st.buf += data[pos:pos + take]
    pos += take
    if len(st.buf) < st.msg_len:
        return None, pos
    payload, st.buf = st.buf, b""
    return RtmpMessage(st.msg_type, st.timestamp, st.stream_id, payload), pos


# ---------------------------------------------------------------- commands

def _split_aggregate(msg: RtmpMessage) -> List[RtmpMessage]:
    """Sub-messages of a type-22 aggregate, timestamps rebased onto the
    aggregate's own timestamp (first sub's stamp is the base)."""
    out: List[RtmpMessage] = []
    data = msg.payload
    pos = 0
    base_ts: Optional[int] = None
    while pos + 11 <= len(data):
        sub_type = data[pos]
        size = int.from_bytes(data[pos + 1:pos + 4], "big")
        # FLV-style timestamp: 3 bytes + 1 extension byte (high bits)
        ts = int.from_bytes(data[pos + 4:pos + 7], "big") | \
            (data[pos + 7] << 24)
        body_start = pos + 11
        body_end = body_start + size
        if body_end > len(data):
            raise RtmpError("aggregate sub-message overruns payload")
        if base_ts is None:
            base_ts = ts
        # clamp: a hostile/malformed aggregate with a sub-tag OLDER than
        # the first would rebase negative and wrap to a far-future u32
        # timestamp in the chunk writer
        out.append(RtmpMessage(sub_type,
                               max(0, msg.timestamp + (ts - base_ts)),
                               msg.stream_id, data[body_start:body_end]))
        pos = body_end + 4      # skip the back-pointer
    return out


def command_message(name: str, transaction_id: float, *vals,
                    stream_id: int = 0) -> RtmpMessage:
    return RtmpMessage(MSG_COMMAND_AMF0, 0, stream_id,
                       amf.encode_values(name, float(transaction_id), *vals))


def _control(msg_type: int, payload: bytes) -> RtmpMessage:
    return RtmpMessage(msg_type, 0, 0, payload)


def _write_msg(socket, msg: RtmpMessage, csid: int = _COMMAND_CSID):
    out = IOBuf()
    out.append(pack_chunks(msg, csid))
    return socket.write(out)


def on_status(stream_id: int, level: str, code: str, desc: str) -> RtmpMessage:
    return command_message(
        "onStatus", 0, None,
        {"level": level, "code": code, "description": desc},
        stream_id=stream_id)


# ------------------------------------------------------------- live streams

class _LiveStream:
    def __init__(self, name: str):
        self.name = name
        self.publisher = None              # (socket, msg_stream_id)
        self.subscribers: List[Tuple[Any, int]] = []  # (socket, stream_id)
        self.metadata: Optional[bytes] = None
        self.avc_seq: Optional[RtmpMessage] = None
        self.aac_seq: Optional[RtmpMessage] = None


class RtmpService:
    """Server-side stream registry + auth hooks (the RtmpService /
    RtmpServerStream surface of rtmp.h, re-shaped as callbacks).

    ``on_publish(name, socket) -> bool`` / ``on_play(name, socket) ->
    bool`` may reject; media relays publisher -> subscribers with
    sequence-header caching."""

    def __init__(self, on_publish: Optional[Callable] = None,
                 on_play: Optional[Callable] = None):
        self.on_publish = on_publish
        self.on_play = on_play
        self._lock = threading.Lock()
        self._streams: Dict[str, _LiveStream] = {}

    def _stream(self, name: str) -> _LiveStream:
        with self._lock:
            s = self._streams.get(name)
            if s is None:
                s = self._streams[name] = _LiveStream(name)
            return s

    def stream_names(self) -> List[str]:
        with self._lock:
            return sorted(self._streams)

    # ------------------------------------------------------------- publish
    def start_publish(self, name: str, socket, stream_id: int) -> bool:
        if self.on_publish is not None and not self.on_publish(name, socket):
            return False
        s = self._stream(name)
        with self._lock:
            if s.publisher is not None and not s.publisher[0].failed:
                return False       # stream busy
            s.publisher = (socket, stream_id)
        return True

    def stop_publish(self, name: str, socket) -> None:
        with self._lock:
            s = self._streams.get(name)
            if s is not None and s.publisher is not None and \
                    s.publisher[0] is socket:
                s.publisher = None
                s.metadata = s.avc_seq = s.aac_seq = None

    # ---------------------------------------------------------------- play
    def start_play(self, name: str, socket, stream_id: int) -> bool:
        if self.on_play is not None and not self.on_play(name, socket):
            return False
        s = self._stream(name)
        # catch-up + subscriber registration under ONE lock hold: written
        # outside it, relay() could slip a live inter-frame in front of
        # the cached codec config (writes are non-blocking enqueues, so
        # holding the lock across them is cheap)
        with self._lock:
            if s.metadata is not None:
                meta_type, meta_payload = s.metadata
                _write_msg(socket, RtmpMessage(meta_type, 0, stream_id,
                                               meta_payload), _MEDIA_CSID)
            for seq in (s.avc_seq, s.aac_seq):
                if seq is not None:
                    _write_msg(socket, RtmpMessage(seq.msg_type, 0,
                                                   stream_id, seq.payload),
                               _MEDIA_CSID)
            s.subscribers.append((socket, stream_id))
        return True

    def stop_play(self, name: str, socket) -> None:
        with self._lock:
            s = self._streams.get(name)
            if s is not None:
                s.subscribers = [(sk, sid) for sk, sid in s.subscribers
                                 if sk is not socket]

    def drop_socket(self, socket) -> None:
        with self._lock:
            for s in self._streams.values():
                if s.publisher is not None and s.publisher[0] is socket:
                    s.publisher = None
                    s.metadata = s.avc_seq = s.aac_seq = None
                s.subscribers = [(sk, sid) for sk, sid in s.subscribers
                                 if sk is not socket]

    # --------------------------------------------------------------- media
    def relay(self, name: str, msg: RtmpMessage, from_socket) -> None:
        s = self._stream(name)
        with self._lock:
            if s.publisher is None or s.publisher[0] is not from_socket:
                return
            if msg.msg_type in (MSG_DATA_AMF0, MSG_DATA_AMF3):
                # cache either encoding's onMetaData for late joiners —
                # WITH its type, so the replay keeps the envelope the
                # payload was encoded for
                s.metadata = (msg.msg_type, msg.payload)
            elif msg.msg_type == MSG_VIDEO and len(msg.payload) >= 2 and \
                    (msg.payload[0] & 0x0F) == 7 and msg.payload[1] == 0:
                s.avc_seq = msg           # AVC sequence header (codec cfg)
            elif msg.msg_type == MSG_AUDIO and len(msg.payload) >= 2 and \
                    (msg.payload[0] >> 4) == 10 and msg.payload[1] == 0:
                s.aac_seq = msg           # AAC sequence header
            targets = list(s.subscribers)
        for sock, sid in targets:
            if sock.failed:
                self.stop_play(name, sock)
                continue
            _write_msg(sock, RtmpMessage(msg.msg_type, msg.timestamp, sid,
                                         msg.payload), _MEDIA_CSID)


# ---------------------------------------------------------------- protocol

class RtmpProtocol(Protocol):
    name = "rtmp"

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        state: Optional[_ConnState] = socket.user_data.get("rtmp_state")
        client = socket.user_data.get("rtmp_client")
        if state is None:
            if client is None:
                first = portal.peek_bytes(1)
                if first != bytes([RTMP_VERSION]):
                    return PARSE_TRY_OTHERS, None
                server = socket.user_data.get("server")
                if server is None or \
                        getattr(server.options, "rtmp_service", None) is None:
                    # a stray 0x03 byte at a non-RTMP server must not
                    # trigger a handshake + per-conn state allocation
                    return PARSE_TRY_OTHERS, None
            state = _ConnState(is_client=client is not None)
            socket.user_data["rtmp_state"] = state
        try:
            return self._parse_with_state(portal, socket, state)
        except (RtmpError, amf.AmfError, struct.error) as e:
            socket.set_failed(ConnectionError(f"rtmp: {e}"))
            return PARSE_NOT_ENOUGH_DATA, None

    def _parse_with_state(self, portal, socket, state: _ConnState):
        if state.phase == _ConnState.PHASE_UNINIT:
            if state.is_client:
                # expect S0+S1+S2
                need = 1 + 2 * HANDSHAKE_SIZE
                if portal.size < need:
                    return PARSE_NOT_ENOUGH_DATA, None
                data = portal.peek_bytes(need)
                if data[0] != RTMP_VERSION:
                    raise RtmpError(f"bad server version {data[0]}")
                portal.pop_front(need)
                s1 = data[1:1 + HANDSHAKE_SIZE]
                c2 = s1   # plain handshake: C2 echoes S1
                if socket.user_data.get("rtmp_c1_digest") is not None:
                    server = _hs_find_digest(s1, _FMS_KEY)
                    if server is not None:
                        # digest server: keyed C2 (a plain server that
                        # echoed our C1 gets the echo path above)
                        c2 = _hs_ack_block(server[1], _FP_KEY + _KEY_TAIL)
                out = IOBuf()
                out.append(c2)
                socket.write(out)
                state.phase = _ConnState.PHASE_READY
                return PARSE_OK, ("rtmp_handshake_done",)
            # server: expect C0+C1
            need = 1 + HANDSHAKE_SIZE
            if portal.size < need:
                return PARSE_NOT_ENOUGH_DATA, None
            data = portal.peek_bytes(need)
            if data[0] != RTMP_VERSION:
                raise RtmpError(f"bad client version {data[0]}")
            portal.pop_front(need)
            c1 = data[1:]
            found = None
            if c1[4:8] != b"\x00\x00\x00\x00":
                # nonzero version word: a stock encoder offering the
                # digest handshake — a bad digest falls back to plain
                # echo rather than refusing the connection
                found = _hs_find_digest(c1, _FP_KEY)
            if found is not None:
                scheme, client_digest = found
                s1, _ = _hs_build_block(_FMS_KEY, scheme,
                                        bytes((3, 5, 1, 1)))
                s2 = _hs_ack_block(client_digest, _FMS_KEY + _KEY_TAIL)
            else:
                s1 = struct.pack(">II", 0, 0) + \
                    os.urandom(HANDSHAKE_SIZE - 8)
                s2 = c1                             # plain: echo C1
            out = IOBuf()
            out.append(bytes([RTMP_VERSION]) + s1 + s2)   # S0 S1 S2
            socket.write(out)
            state.phase = _ConnState.PHASE_ACK
            # PARSE_OK (not NOT_ENOUGH_DATA) so the messenger records rtmp
            # as this socket's preferred protocol NOW — later handshake/
            # chunk bytes are random-looking and must never be offered to
            # other parsers first
            return PARSE_OK, ("rtmp_handshake_progress",)
        if state.phase == _ConnState.PHASE_ACK:
            if portal.size < HANDSHAKE_SIZE:
                return PARSE_NOT_ENOUGH_DATA, None
            portal.pop_front(HANDSHAKE_SIZE)   # C2: ignored (plain handshake)
            state.phase = _ConnState.PHASE_READY
            return PARSE_OK, ("rtmp_handshake_progress",)

        data = portal.peek_bytes(portal.size)
        msgs: List[RtmpMessage] = []
        pos = 0
        while pos < len(data):
            got = _parse_one_chunk(state, data, pos)
            if got is None:
                break
            msg, pos = got
            if msg is None:
                continue
            # connection-control messages mutate parse state IN ORDER
            if msg.msg_type == MSG_SET_CHUNK_SIZE and len(msg.payload) >= 4:
                size = struct.unpack(">I", msg.payload[:4])[0] & 0x7FFFFFFF
                if not 1 <= size <= 0xFFFFFF:
                    raise RtmpError(f"bad chunk size {size}")
                state.in_chunk_size = size
                continue
            if msg.msg_type == MSG_ABORT and len(msg.payload) >= 4:
                aborted = struct.unpack(">I", msg.payload[:4])[0]
                st = state.csids.get(aborted)
                if st is not None:
                    st.buf = b""
                continue
            if msg.msg_type in (MSG_ACK, MSG_WINDOW_ACK_SIZE,
                                MSG_SET_PEER_BW, MSG_USER_CONTROL):
                continue       # bookkeeping only; no app dispatch
            if msg.msg_type == MSG_AGGREGATE:
                # split into its sub-messages (the reference handles
                # type 22 the same way): each sub carries an 11-byte
                # FLV-shaped tag header + body + 4-byte back-pointer;
                # the first sub's timestamp is the base the aggregate's
                # own timestamp replaces, deltas are preserved
                msgs.extend(_split_aggregate(msg))
                continue
            msgs.append(msg)
        if pos:
            portal.pop_front(pos)
        if not msgs:
            return PARSE_NOT_ENOUGH_DATA, None
        return PARSE_OK, msgs

    # -------------------------------------------------------------- process
    def process_inline(self, msgs, socket) -> bool:
        if isinstance(msgs, tuple):
            if msgs and msgs[0] == "rtmp_handshake_done":
                client = socket.user_data.get("rtmp_client")
                if client is not None:
                    client._on_handshake_done()
            return True   # progress markers need no dispatch
        client = socket.user_data.get("rtmp_client")
        if client is not None:
            for m in msgs:
                client._on_message(m)
            return True
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        for m in msgs:
            process_in_parse_order(socket, "rtmp", m, self._serve)
        return True

    async def _serve(self, msg: RtmpMessage, socket):
        server = socket.user_data.get("server")
        service: Optional[RtmpService] = (
            getattr(server.options, "rtmp_service", None)
            if server is not None else None)
        if service is None:
            socket.set_failed(ConnectionError("no rtmp_service installed"))
            return
        state: _ConnState = socket.user_data["rtmp_state"]
        if socket.user_data.get("rtmp_cleanup") is None:
            socket.user_data["rtmp_cleanup"] = True
            socket.on_failed(service.drop_socket)
        if msg.msg_type == MSG_COMMAND_AMF0:
            await self._serve_command(msg, socket, service, state, server)
        elif msg.msg_type == MSG_COMMAND_AMF3:
            # AMF3 command envelope: one leading format byte (0x00),
            # then AMF0 values which may themselves switch to AMF3 via
            # the 0x11 avmplus marker — amf.decode_value handles both
            body = msg.payload[1:] if msg.payload[:1] == b"\x00" \
                else msg.payload
            inner = RtmpMessage(MSG_COMMAND_AMF0, msg.timestamp,
                                msg.stream_id, body)
            await self._serve_command(inner, socket, service, state, server)
        elif msg.msg_type in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0,
                              MSG_DATA_AMF3):
            name = socket.user_data.get("rtmp_pub_name")
            if name:
                service.relay(name, msg, socket)

    async def _serve_command(self, msg, socket, service, state, server):
        vals = amf.decode_all(msg.payload)
        if not vals or not isinstance(vals[0], str):
            raise RtmpError("malformed command")
        name = vals[0]
        tid = vals[1] if len(vals) > 1 else 0
        if name == "connect":
            obj = vals[2] if len(vals) > 2 and isinstance(vals[2], dict) else {}
            state.app = str(obj.get("app", ""))
            _write_msg(socket, _control(MSG_WINDOW_ACK_SIZE,
                                        struct.pack(">I", 2500000)),
                       _CONTROL_CSID)
            _write_msg(socket, _control(MSG_SET_PEER_BW,
                                        struct.pack(">IB", 2500000, 2)),
                       _CONTROL_CSID)
            _write_msg(socket, _control(MSG_SET_CHUNK_SIZE,
                                        struct.pack(">I", OUT_CHUNK_SIZE)),
                       _CONTROL_CSID)
            _write_msg(socket, command_message(
                "_result", tid,
                {"fmsVer": "BRPC-TPU/1,0", "capabilities": 31.0},
                {"level": "status", "code": "NetConnection.Connect.Success",
                 "description": "Connection succeeded.",
                 "objectEncoding": 0.0}))
        elif name == "createStream":
            sid = state.next_stream_id
            state.next_stream_id += 1
            _write_msg(socket, command_message("_result", tid, None,
                                               float(sid)))
        elif name == "publish":
            stream_name = vals[3] if len(vals) > 3 else ""
            if not isinstance(stream_name, str) or not stream_name:
                raise RtmpError("publish without stream name")
            if service.start_publish(stream_name, socket, msg.stream_id):
                socket.user_data["rtmp_pub_name"] = stream_name
                _write_msg(socket, on_status(
                    msg.stream_id, "status", "NetStream.Publish.Start",
                    f"Publishing {stream_name}."))
            else:
                _write_msg(socket, on_status(
                    msg.stream_id, "error", "NetStream.Publish.BadName",
                    f"Stream {stream_name} is busy or rejected."))
        elif name == "play":
            stream_name = vals[3] if len(vals) > 3 else ""
            if not isinstance(stream_name, str) or not stream_name:
                raise RtmpError("play without stream name")
            if service.start_play(stream_name, socket, msg.stream_id):
                socket.user_data.setdefault("rtmp_play_names", set()).add(
                    stream_name)
                _write_msg(socket, on_status(
                    msg.stream_id, "status", "NetStream.Play.Start",
                    f"Playing {stream_name}."))
            else:
                _write_msg(socket, on_status(
                    msg.stream_id, "error", "NetStream.Play.StreamNotFound",
                    f"Play {stream_name} rejected."))
        elif name in ("deleteStream", "closeStream", "FCUnpublish"):
            pub = socket.user_data.pop("rtmp_pub_name", None)
            if pub:
                service.stop_publish(pub, socket)
            for pname in socket.user_data.pop("rtmp_play_names", set()):
                service.stop_play(pname, socket)
        elif name in ("releaseStream", "FCPublish", "getStreamLength"):
            _write_msg(socket, command_message("_result", tid, None, None))
        # unknown commands are ignored (the reference logs and continues)

    def process(self, msg, socket):
        raise AssertionError("rtmp messages are processed inline")


# ------------------------------------------------------------------ client

class RtmpClient:
    """Publish/play client (RtmpClient + RtmpClientStream of rtmp.h).

    ``client = RtmpClient(ep, app="live"); client.connect()``
    then ``sid = client.create_stream(); client.publish(sid, "room")``
    and ``client.send_video(sid, ts, payload)`` — or ``client.play(sid,
    "room", on_media=cb)`` to receive the relay."""

    def __init__(self, address: str | EndPoint, app: str = "live",
                 timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address))
        self.app = app
        self._timeout_s = timeout_s
        self._control = control or global_control()
        self._messenger = InputMessenger(protocols=[ensure_registered()],
                                         control=self._control)
        self._lock = threading.Lock()
        self._socket = None
        self._handshake_done = FiberEvent()
        self._handshake_socket = None            # socket the gate guards
        self._next_tid = 1
        self._pending: Dict[float, list] = {}    # tid -> [event, result, sock]
        self._status_waiters: deque = deque()    # [event, payload, sock]
        self.on_media: Optional[Callable[[RtmpMessage], None]] = None

    # ------------------------------------------------------------ plumbing
    def _get_socket(self):
        with self._lock:
            existing = self._socket
            gate = self._handshake_done
        if existing is not None and not existing.failed:
            # the winner may still be mid-handshake (another fiber created
            # it and is waiting): every caller path gates before writing
            if not gate.wait_pthread(self._timeout_s):
                raise TimeoutError("rtmp handshake timed out")
            if not existing.failed:
                return existing
        sock = create_client_socket(
            self._endpoint, on_input=self._messenger.on_new_messages,
            control=self._control)
        sock.user_data["rtmp_client"] = self
        sock.on_failed(self._on_failed)
        with self._lock:
            if self._socket is not None and not self._socket.failed:
                loser, sock = sock, self._socket
            else:
                self._socket, loser = sock, None
                # fresh handshake gate: the old (set) event must not let a
                # reconnecting caller write commands mid-handshake — the
                # server would eat them as C2 bytes
                self._handshake_done = FiberEvent()
                self._handshake_socket = sock
                # C0 + C1 — digest handshake by default (the shape stock
                # encoders send; our server and plain-echo servers both
                # accept it, since a server that doesn't validate
                # digests just echoes C1 back)
                c1, c1_digest = _hs_build_block(_FP_KEY, 0,
                                                bytes((127, 101, 0, 1)))
                sock.user_data["rtmp_c1_digest"] = c1_digest
                out = IOBuf()
                out.append(bytes([RTMP_VERSION]) + c1)
                sock.write(out)
        if loser is not None:
            loser.set_failed(ConnectionError("duplicate connect discarded"))
        # no command may be written before S0S1S2+C2 complete (the server
        # would consume it as C2 bytes); every caller path gates here
        with self._lock:
            gate = self._handshake_done
        if not gate.wait_pthread(self._timeout_s):
            sock.set_failed(TimeoutError("rtmp handshake timed out"))
            raise TimeoutError("rtmp handshake timed out")
        if sock.failed:
            raise ConnectionError("rtmp connection failed during handshake")
        return sock

    def _on_failed(self, socket):
        # Per-socket flush: a discarded duplicate-connect loser must not
        # flush calls in flight on the winner, nor release the winner's
        # handshake gate early (callers would write commands the server
        # consumes as C2 bytes, desyncing the winning connection). Slots
        # are tagged with the socket they were written to.
        err = getattr(socket, "fail_reason", None) or \
            ConnectionError("rtmp connection failed")
        with self._lock:
            if self._socket is socket:
                self._socket = None
            pending = {t: s for t, s in self._pending.items()
                       if s[2] is socket}
            for t in pending:
                del self._pending[t]
            waiters = [s for s in self._status_waiters if s[2] is socket]
            for s in waiters:
                self._status_waiters.remove(s)
            handshake = (self._handshake_done
                         if self._handshake_socket is socket else None)
        if handshake is not None:
            handshake.set()   # wake _get_socket waiters; they see .failed
        for slot in pending.values():
            slot[1] = err
            slot[0].set()
        for slot in waiters:
            slot[1] = err
            slot[0].set()

    def _on_handshake_done(self):
        self._handshake_done.set()

    def _on_message(self, msg: RtmpMessage):
        if msg.msg_type == MSG_COMMAND_AMF0:
            vals = amf.decode_all(msg.payload)
            if not vals:
                return
            if vals[0] in ("_result", "_error"):
                tid = float(vals[1]) if len(vals) > 1 else 0.0
                with self._lock:
                    slot = self._pending.pop(tid, None)
                if slot is not None:
                    slot[1] = (vals[0], vals[2:])
                    slot[0].set()
            elif vals[0] == "onStatus":
                info = next((v for v in vals[2:] if isinstance(v, dict)), {})
                with self._lock:
                    slot = self._status_waiters.popleft() \
                        if self._status_waiters else None
                if slot is not None:
                    slot[1] = info
                    slot[0].set()
        elif msg.msg_type in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
            cb = self.on_media
            if cb is not None:
                cb(msg)

    def _call(self, name: str, *vals, stream_id: int = 0):
        sock = self._get_socket()
        with self._lock:
            tid = float(self._next_tid)
            self._next_tid += 1
            slot = [FiberEvent(), None, sock]
            self._pending[tid] = slot
        _write_msg(sock, command_message(name, tid, *vals,
                                         stream_id=stream_id))
        if not slot[0].wait_pthread(self._timeout_s):
            with self._lock:
                self._pending.pop(tid, None)
            raise TimeoutError(f"rtmp {name} timed out")
        if isinstance(slot[1], BaseException):
            raise slot[1]
        kind, rest = slot[1]
        if kind == "_error":
            raise RtmpError(f"{name} failed: {rest}")
        return rest

    def _wait_status(self, sock, send_fn, what: str) -> dict:
        slot = [FiberEvent(), None, sock]
        with self._lock:
            self._status_waiters.append(slot)
        send_fn()
        if not slot[0].wait_pthread(self._timeout_s):
            with self._lock:
                try:
                    self._status_waiters.remove(slot)
                except ValueError:
                    pass
            raise TimeoutError(f"rtmp {what} timed out")
        if isinstance(slot[1], BaseException):
            raise slot[1]
        info = slot[1] or {}
        if info.get("level") == "error":
            raise RtmpError(f"{what} rejected: {info.get('code')}")
        return info

    # ----------------------------------------------------------------- api
    def connect(self) -> dict:
        self._get_socket()   # connects + waits out the handshake
        rest = self._call("connect", {"app": self.app, "flashVer": "BRPC-TPU",
                                      "tcUrl": f"rtmp://{self._endpoint}/"
                                               f"{self.app}",
                                      "objectEncoding": 0.0})
        info = next((v for v in rest if isinstance(v, dict)
                     and "code" in v), {})
        if info.get("code") != "NetConnection.Connect.Success":
            raise RtmpError(f"connect rejected: {info}")
        return info

    def create_stream(self) -> int:
        rest = self._call("createStream", None)
        for v in rest:
            if isinstance(v, float):
                return int(v)
        raise RtmpError("createStream returned no stream id")

    def publish(self, stream_id: int, name: str) -> dict:
        sock = self._get_socket()
        return self._wait_status(
            sock,
            lambda: _write_msg(sock, command_message(
                "publish", 0, None, name, "live", stream_id=stream_id)),
            f"publish {name!r}")

    def play(self, stream_id: int, name: str,
             on_media: Optional[Callable] = None) -> dict:
        if on_media is not None:
            self.on_media = on_media
        sock = self._get_socket()
        return self._wait_status(
            sock,
            lambda: _write_msg(sock, command_message(
                "play", 0, None, name, -2000.0, stream_id=stream_id)),
            f"play {name!r}")

    def _send_media(self, msg_type: int, stream_id: int, timestamp: int,
                    payload: bytes):
        sock = self._get_socket()
        _write_msg(sock, RtmpMessage(msg_type, timestamp, stream_id,
                                     payload), _MEDIA_CSID)

    def send_video(self, stream_id: int, timestamp: int, payload: bytes):
        self._send_media(MSG_VIDEO, stream_id, timestamp, payload)

    def send_audio(self, stream_id: int, timestamp: int, payload: bytes):
        self._send_media(MSG_AUDIO, stream_id, timestamp, payload)

    def send_metadata(self, stream_id: int, metadata: dict):
        self._send_media(MSG_DATA_AMF0, stream_id, 0,
                         amf.encode_values("onMetaData",
                                           amf.AmfEcmaArray(metadata)))

    def close(self):
        with self._lock:
            s, self._socket = self._socket, None
        if s is not None and not s.failed:
            s.set_failed(ConnectionError("rtmp client closed"))


_instance: Optional[RtmpProtocol] = None


def ensure_registered() -> RtmpProtocol:
    global _instance
    if _instance is None:
        _instance = RtmpProtocol()
        register_protocol(_instance)
    return _instance
