"""FLV container mux/demux (the FLV writer half of the reference's rtmp
stack, rtmp.cpp FlvWriter/ts.cpp; tag type ids are the RTMP message
types, so RTMP media messages drop straight into tags)."""

from __future__ import annotations

import struct
from typing import Iterator, NamedTuple, Optional

FLV_HEADER_AUDIO = 0x04
FLV_HEADER_VIDEO = 0x01

TAG_AUDIO = 8
TAG_VIDEO = 9
TAG_SCRIPT = 18


class FlvTag(NamedTuple):
    tag_type: int
    timestamp: int
    payload: bytes


class FlvError(Exception):
    pass


def file_header(has_audio: bool = True, has_video: bool = True) -> bytes:
    flags = (FLV_HEADER_AUDIO if has_audio else 0) | \
        (FLV_HEADER_VIDEO if has_video else 0)
    return b"FLV\x01" + bytes([flags]) + struct.pack(">I", 9) + \
        struct.pack(">I", 0)   # PreviousTagSize0


def pack_tag(tag: FlvTag) -> bytes:
    ts = tag.timestamp & 0xFFFFFFFF
    head = bytes([tag.tag_type]) + \
        struct.pack(">I", len(tag.payload))[1:] + \
        struct.pack(">I", ts & 0xFFFFFF)[1:] + bytes([(ts >> 24) & 0xFF]) + \
        b"\x00\x00\x00"
    return head + tag.payload + struct.pack(">I", 11 + len(tag.payload))


def parse_header(data: bytes) -> int:
    """Validates the 9-byte header + PreviousTagSize0; returns the offset
    of the first tag."""
    if len(data) < 13:
        raise FlvError("short flv header")
    if data[:4] != b"FLV\x01":
        raise FlvError("bad flv signature")
    offset = struct.unpack(">I", data[5:9])[0]
    if offset < 9:
        raise FlvError("bad flv data offset")
    return offset + 4


def iter_tags(data: bytes, pos: Optional[int] = None) -> Iterator[FlvTag]:
    if pos is None:
        pos = parse_header(data)
    while pos + 11 <= len(data):
        tag_type = data[pos]
        size = int.from_bytes(data[pos + 1:pos + 4], "big")
        ts = int.from_bytes(data[pos + 4:pos + 7], "big") | \
            (data[pos + 7] << 24)
        if pos + 11 + size + 4 > len(data):
            raise FlvError("truncated flv tag")
        payload = data[pos + 11:pos + 11 + size]
        prev = struct.unpack(">I", data[pos + 11 + size:pos + 15 + size])[0]
        if prev != 11 + size:
            raise FlvError("bad PreviousTagSize")
        yield FlvTag(tag_type, ts, payload)
        pos += 11 + size + 4
