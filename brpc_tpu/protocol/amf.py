"""AMF0 codec (src/brpc/amf.{h,cpp}, 1211 LoC in the reference): the
serialization under RTMP command messages.

Python mapping: float/int -> number, bool -> boolean, str -> string
(long string when >64KB), dict -> object, AmfEcmaArray -> ECMA array,
list -> strict array, None -> null, Undefined -> undefined."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

_MAX_DEPTH = 32

# markers
_NUMBER = 0x00
_BOOLEAN = 0x01
_STRING = 0x02
_OBJECT = 0x03
_NULL = 0x05
_UNDEFINED = 0x06
_ECMA_ARRAY = 0x08
_OBJECT_END = 0x09
_STRICT_ARRAY = 0x0A
_DATE = 0x0B
_LONG_STRING = 0x0C


class Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "amf.Undefined"


class AmfEcmaArray(dict):
    """dict subclass marking ECMA-array encoding."""


class AmfDate(float):
    """milliseconds since epoch (timezone field written as 0)."""


class AmfError(Exception):
    pass


# ----------------------------------------------------------------- encode

def _encode_utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise AmfError("property name too long")
    return struct.pack(">H", len(b)) + b


def encode_value(v, depth: int = 0) -> bytes:
    if depth > _MAX_DEPTH:
        raise AmfError("AMF nesting too deep")
    if isinstance(v, Undefined):
        return bytes([_UNDEFINED])
    if v is None:
        return bytes([_NULL])
    if isinstance(v, bool):
        return bytes([_BOOLEAN, 1 if v else 0])
    if isinstance(v, AmfDate):
        return bytes([_DATE]) + struct.pack(">dH", float(v), 0)
    if isinstance(v, (int, float)):
        return bytes([_NUMBER]) + struct.pack(">d", float(v))
    if isinstance(v, str):
        b = v.encode("utf-8")
        if len(b) > 0xFFFF:
            return bytes([_LONG_STRING]) + struct.pack(">I", len(b)) + b
        return bytes([_STRING]) + struct.pack(">H", len(b)) + b
    if isinstance(v, AmfEcmaArray):
        out = [bytes([_ECMA_ARRAY]), struct.pack(">I", len(v))]
        for k, val in v.items():
            out.append(_encode_utf8(str(k)))
            out.append(encode_value(val, depth + 1))
        out.append(b"\x00\x00" + bytes([_OBJECT_END]))
        return b"".join(out)
    if isinstance(v, dict):
        out = [bytes([_OBJECT])]
        for k, val in v.items():
            out.append(_encode_utf8(str(k)))
            out.append(encode_value(val, depth + 1))
        out.append(b"\x00\x00" + bytes([_OBJECT_END]))
        return b"".join(out)
    if isinstance(v, (list, tuple)):
        out = [bytes([_STRICT_ARRAY]), struct.pack(">I", len(v))]
        for val in v:
            out.append(encode_value(val, depth + 1))
        return b"".join(out)
    raise AmfError(f"cannot encode {type(v)!r}")


def encode_values(*values) -> bytes:
    return b"".join(encode_value(v) for v in values)


# ----------------------------------------------------------------- decode

def _read_utf8(data: bytes, pos: int) -> Tuple[str, int]:
    if pos + 2 > len(data):
        raise AmfError("truncated name")
    n = struct.unpack_from(">H", data, pos)[0]
    if pos + 2 + n > len(data):
        raise AmfError("truncated name body")
    return data[pos + 2:pos + 2 + n].decode("utf-8", "replace"), pos + 2 + n


def decode_value(data: bytes, pos: int = 0, depth: int = 0) -> Tuple[Any, int]:
    if depth > _MAX_DEPTH:
        raise AmfError("AMF nesting too deep")
    if pos >= len(data):
        raise AmfError("truncated value")
    marker = data[pos]
    pos += 1
    if marker == _NUMBER:
        if pos + 8 > len(data):
            raise AmfError("truncated number")
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if marker == _BOOLEAN:
        if pos + 1 > len(data):
            raise AmfError("truncated boolean")
        return data[pos] != 0, pos + 1
    if marker == _STRING:
        return _read_utf8(data, pos)
    if marker == _LONG_STRING:
        if pos + 4 > len(data):
            raise AmfError("truncated long string")
        n = struct.unpack_from(">I", data, pos)[0]
        if pos + 4 + n > len(data):
            raise AmfError("truncated long string body")
        return data[pos + 4:pos + 4 + n].decode("utf-8", "replace"), \
            pos + 4 + n
    if marker in (_OBJECT, _ECMA_ARRAY):
        out: Dict[str, Any] = AmfEcmaArray() if marker == _ECMA_ARRAY else {}
        if marker == _ECMA_ARRAY:
            if pos + 4 > len(data):
                raise AmfError("truncated ecma array")
            pos += 4   # associative count is advisory
        while True:
            name, pos = _read_utf8(data, pos)
            if name == "" and pos < len(data) and data[pos] == _OBJECT_END:
                return out, pos + 1
            out[name], pos = decode_value(data, pos, depth + 1)
    if marker == _NULL:
        return None, pos
    if marker == _UNDEFINED:
        return Undefined(), pos
    if marker == _STRICT_ARRAY:
        if pos + 4 > len(data):
            raise AmfError("truncated strict array")
        n = struct.unpack_from(">I", data, pos)[0]
        if n > len(data):        # each element is >=1 byte
            raise AmfError("bad strict array length")
        pos += 4
        out_l: List[Any] = []
        for _ in range(n):
            v, pos = decode_value(data, pos, depth + 1)
            out_l.append(v)
        return out_l, pos
    if marker == _DATE:
        if pos + 10 > len(data):
            raise AmfError("truncated date")
        ms = struct.unpack_from(">d", data, pos)[0]
        return AmfDate(ms), pos + 10
    raise AmfError(f"unsupported AMF0 marker 0x{marker:02x}")


def decode_all(data: bytes) -> List[Any]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode_value(data, pos)
        out.append(v)
    return out
