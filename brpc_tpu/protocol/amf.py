"""AMF0 codec (src/brpc/amf.{h,cpp}, 1211 LoC in the reference): the
serialization under RTMP command messages.

Python mapping: float/int -> number, bool -> boolean, str -> string
(long string when >64KB), dict -> object, AmfEcmaArray -> ECMA array,
list -> strict array, None -> null, Undefined -> undefined."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

_MAX_DEPTH = 32

# markers
_NUMBER = 0x00
_BOOLEAN = 0x01
_STRING = 0x02
_OBJECT = 0x03
_NULL = 0x05
_UNDEFINED = 0x06
_ECMA_ARRAY = 0x08
_OBJECT_END = 0x09
_STRICT_ARRAY = 0x0A
_DATE = 0x0B
_AVMPLUS = 0x11   # switch-to-AMF3 marker (objectEncoding 3)
_LONG_STRING = 0x0C


class Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "amf.Undefined"


class AmfEcmaArray(dict):
    """dict subclass marking ECMA-array encoding."""


class AmfDate(float):
    """milliseconds since epoch (timezone field written as 0)."""


class AmfError(Exception):
    pass


# ----------------------------------------------------------------- encode

def _encode_utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise AmfError("property name too long")
    return struct.pack(">H", len(b)) + b


def encode_value(v, depth: int = 0) -> bytes:
    if depth > _MAX_DEPTH:
        raise AmfError("AMF nesting too deep")
    if isinstance(v, Undefined):
        return bytes([_UNDEFINED])
    if v is None:
        return bytes([_NULL])
    if isinstance(v, bool):
        return bytes([_BOOLEAN, 1 if v else 0])
    if isinstance(v, AmfDate):
        return bytes([_DATE]) + struct.pack(">dH", float(v), 0)
    if isinstance(v, (int, float)):
        return bytes([_NUMBER]) + struct.pack(">d", float(v))
    if isinstance(v, str):
        b = v.encode("utf-8")
        if len(b) > 0xFFFF:
            return bytes([_LONG_STRING]) + struct.pack(">I", len(b)) + b
        return bytes([_STRING]) + struct.pack(">H", len(b)) + b
    if isinstance(v, AmfEcmaArray):
        out = [bytes([_ECMA_ARRAY]), struct.pack(">I", len(v))]
        for k, val in v.items():
            out.append(_encode_utf8(str(k)))
            out.append(encode_value(val, depth + 1))
        out.append(b"\x00\x00" + bytes([_OBJECT_END]))
        return b"".join(out)
    if isinstance(v, dict):
        out = [bytes([_OBJECT])]
        for k, val in v.items():
            out.append(_encode_utf8(str(k)))
            out.append(encode_value(val, depth + 1))
        out.append(b"\x00\x00" + bytes([_OBJECT_END]))
        return b"".join(out)
    if isinstance(v, (list, tuple)):
        out = [bytes([_STRICT_ARRAY]), struct.pack(">I", len(v))]
        for val in v:
            out.append(encode_value(val, depth + 1))
        return b"".join(out)
    raise AmfError(f"cannot encode {type(v)!r}")


def encode_values(*values) -> bytes:
    return b"".join(encode_value(v) for v in values)


# ----------------------------------------------------------------- decode

def _read_utf8(data: bytes, pos: int) -> Tuple[str, int]:
    if pos + 2 > len(data):
        raise AmfError("truncated name")
    n = struct.unpack_from(">H", data, pos)[0]
    if pos + 2 + n > len(data):
        raise AmfError("truncated name body")
    return data[pos + 2:pos + 2 + n].decode("utf-8", "replace"), pos + 2 + n


def decode_value(data: bytes, pos: int = 0, depth: int = 0) -> Tuple[Any, int]:
    if depth > _MAX_DEPTH:
        raise AmfError("AMF nesting too deep")
    if pos >= len(data):
        raise AmfError("truncated value")
    marker = data[pos]
    pos += 1
    if marker == _NUMBER:
        if pos + 8 > len(data):
            raise AmfError("truncated number")
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if marker == _BOOLEAN:
        if pos + 1 > len(data):
            raise AmfError("truncated boolean")
        return data[pos] != 0, pos + 1
    if marker == _STRING:
        return _read_utf8(data, pos)
    if marker == _LONG_STRING:
        if pos + 4 > len(data):
            raise AmfError("truncated long string")
        n = struct.unpack_from(">I", data, pos)[0]
        if pos + 4 + n > len(data):
            raise AmfError("truncated long string body")
        return data[pos + 4:pos + 4 + n].decode("utf-8", "replace"), \
            pos + 4 + n
    if marker in (_OBJECT, _ECMA_ARRAY):
        out: Dict[str, Any] = AmfEcmaArray() if marker == _ECMA_ARRAY else {}
        if marker == _ECMA_ARRAY:
            if pos + 4 > len(data):
                raise AmfError("truncated ecma array")
            pos += 4   # associative count is advisory
        while True:
            name, pos = _read_utf8(data, pos)
            if name == "" and pos < len(data) and data[pos] == _OBJECT_END:
                return out, pos + 1
            out[name], pos = decode_value(data, pos, depth + 1)
    if marker == _NULL:
        return None, pos
    if marker == _UNDEFINED:
        return Undefined(), pos
    if marker == _STRICT_ARRAY:
        if pos + 4 > len(data):
            raise AmfError("truncated strict array")
        n = struct.unpack_from(">I", data, pos)[0]
        if n > len(data):        # each element is >=1 byte
            raise AmfError("bad strict array length")
        pos += 4
        out_l: List[Any] = []
        for _ in range(n):
            v, pos = decode_value(data, pos, depth + 1)
            out_l.append(v)
        return out_l, pos
    if marker == _DATE:
        if pos + 10 > len(data):
            raise AmfError("truncated date")
        ms = struct.unpack_from(">d", data, pos)[0]
        return AmfDate(ms), pos + 10
    if marker == _AVMPLUS:
        # AMF0 -> AMF3 switch (objectEncoding 3 peers): the next value
        # is AMF3-encoded
        return decode_amf3(data, pos)
    raise AmfError(f"unsupported AMF0 marker 0x{marker:02x}")


def decode_all(data: bytes) -> List[Any]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode_value(data, pos)
        out.append(v)
    return out


# ------------------------------------------------------------------ AMF3
# Read-side AMF3 (the reference's amf.cpp AMF3 half): enough of the
# format to decode what objectEncoding-3 encoders actually emit —
# undefined/null/bool/integer(U29)/double/string/date/array/object/
# bytearray, with the string/complex-object reference tables.

_A3_UNDEFINED = 0x00
_A3_NULL = 0x01
_A3_FALSE = 0x02
_A3_TRUE = 0x03
_A3_INTEGER = 0x04
_A3_DOUBLE = 0x05
_A3_STRING = 0x06
_A3_DATE = 0x08
_A3_ARRAY = 0x09
_A3_OBJECT = 0x0A
_A3_BYTEARRAY = 0x0C


class _Amf3Ctx:
    __slots__ = ("strings", "complexes", "traits")

    def __init__(self):
        self.strings: List[str] = []
        self.complexes: List[Any] = []
        self.traits: List[tuple] = []


def _read_u29(data: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    for i in range(4):
        if pos >= len(data):
            raise AmfError("truncated U29")
        b = data[pos]
        pos += 1
        if i < 3:
            v = (v << 7) | (b & 0x7F)
            if not b & 0x80:
                return v, pos
        else:
            return (v << 8) | b, pos
    raise AmfError("unreachable U29")


def _read_a3_string(data: bytes, pos: int, ctx: _Amf3Ctx) -> Tuple[str, int]:
    ref, pos = _read_u29(data, pos)
    if not ref & 1:
        idx = ref >> 1
        if idx >= len(ctx.strings):
            raise AmfError("AMF3 string reference out of range")
        return ctx.strings[idx], pos
    n = ref >> 1
    if pos + n > len(data):
        raise AmfError("truncated AMF3 string")
    s = data[pos:pos + n].decode("utf-8", "replace")
    if s:                      # the empty string is never table-stored
        ctx.strings.append(s)
    return s, pos + n


def decode_amf3(data: bytes, pos: int = 0, ctx: Optional[_Amf3Ctx] = None,
                depth: int = 0) -> Tuple[Any, int]:
    if ctx is None:
        ctx = _Amf3Ctx()
    if depth > _MAX_DEPTH:
        raise AmfError("AMF3 nesting too deep")
    if pos >= len(data):
        raise AmfError("truncated AMF3 value")
    marker = data[pos]
    pos += 1
    if marker == _A3_UNDEFINED:
        return Undefined(), pos
    if marker == _A3_NULL:
        return None, pos
    if marker == _A3_FALSE:
        return False, pos
    if marker == _A3_TRUE:
        return True, pos
    if marker == _A3_INTEGER:
        v, pos = _read_u29(data, pos)
        if v & 0x10000000:      # 29-bit two's complement
            v -= 0x20000000
        return v, pos
    if marker == _A3_DOUBLE:
        if pos + 8 > len(data):
            raise AmfError("truncated AMF3 double")
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if marker == _A3_STRING:
        return _read_a3_string(data, pos, ctx)
    if marker == _A3_DATE:
        ref, pos = _read_u29(data, pos)
        if not ref & 1:
            idx = ref >> 1
            if idx >= len(ctx.complexes):
                raise AmfError("AMF3 date reference out of range")
            return ctx.complexes[idx], pos
        if pos + 8 > len(data):
            raise AmfError("truncated AMF3 date")
        d = AmfDate(struct.unpack_from(">d", data, pos)[0])
        ctx.complexes.append(d)
        return d, pos + 8
    if marker == _A3_ARRAY:
        ref, pos = _read_u29(data, pos)
        if not ref & 1:
            idx = ref >> 1
            if idx >= len(ctx.complexes):
                raise AmfError("AMF3 array reference out of range")
            return ctx.complexes[idx], pos
        dense_n = ref >> 1
        # associative part first (name/value pairs until empty name)
        assoc: Dict[str, Any] = {}
        while True:
            name, pos = _read_a3_string(data, pos, ctx)
            if name == "":
                break
            assoc[name], pos = decode_amf3(data, pos, ctx, depth + 1)
        dense: List[Any] = []
        result: Any = assoc if assoc else dense
        ctx.complexes.append(result)
        for _ in range(dense_n):
            v, pos = decode_amf3(data, pos, ctx, depth + 1)
            dense.append(v)
        if assoc and dense:
            # mixed array: dense part lands under numeric keys
            for i, v in enumerate(dense):
                assoc[str(i)] = v
        return result, pos
    if marker == _A3_OBJECT:
        ref, pos = _read_u29(data, pos)
        if not ref & 1:
            idx = ref >> 1
            if idx >= len(ctx.complexes):
                raise AmfError("AMF3 object reference out of range")
            return ctx.complexes[idx], pos
        if not ref & 2:         # traits reference
            t_idx = ref >> 2
            if t_idx >= len(ctx.traits):
                raise AmfError("AMF3 traits reference out of range")
            class_name, sealed, dynamic = ctx.traits[t_idx]
        elif ref & 4:
            raise AmfError("AMF3 externalizable objects unsupported")
        else:
            dynamic = bool(ref & 8)
            sealed_n = ref >> 4
            class_name, pos = _read_a3_string(data, pos, ctx)
            sealed = []
            for _ in range(sealed_n):
                nm, pos = _read_a3_string(data, pos, ctx)
                sealed.append(nm)
            ctx.traits.append((class_name, sealed, dynamic))
        obj: Dict[str, Any] = {}
        ctx.complexes.append(obj)
        for nm in sealed:
            obj[nm], pos = decode_amf3(data, pos, ctx, depth + 1)
        if dynamic:
            while True:
                nm, pos = _read_a3_string(data, pos, ctx)
                if nm == "":
                    break
                obj[nm], pos = decode_amf3(data, pos, ctx, depth + 1)
        return obj, pos
    if marker == _A3_BYTEARRAY:
        ref, pos = _read_u29(data, pos)
        if not ref & 1:
            idx = ref >> 1
            if idx >= len(ctx.complexes):
                raise AmfError("AMF3 bytearray reference out of range")
            return ctx.complexes[idx], pos
        n = ref >> 1
        if pos + n > len(data):
            raise AmfError("truncated AMF3 bytearray")
        b = data[pos:pos + n]
        ctx.complexes.append(b)
        return b, pos + n
    raise AmfError(f"unsupported AMF3 marker 0x{marker:02x}")


def decode_all_amf3(data: bytes) -> List[Any]:
    ctx = _Amf3Ctx()
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode_amf3(data, pos, ctx)
        out.append(v)
    return out
