"""Protocol registry: one object per wire format, registered globally
(the fn-pointer table of brpc/protocol.h:77-166 and the global table in
global.cpp:401-581).

A Protocol provides:
  parse(portal, socket) -> (status, msg)
      Cut one complete message off the portal. MUST be peek-only unless
      returning PARSE_OK (the InputMessenger retries other protocols on
      PARSE_TRY_OTHERS). Returns:
        PARSE_OK              — msg cut and returned
        PARSE_NOT_ENOUGH_DATA — bytes are mine but incomplete; wait
        PARSE_TRY_OTHERS      — not my framing
  process(msg, socket)    — handle one inbound message (runs in a fiber;
                            may be async). Client and server sides both
                            land here, like process_request/response.
  serialize_request / pack_request — client-side encoding hooks used by
      Channel/Controller (protocol.h serialize_request/pack_request).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

PARSE_OK = "ok"
PARSE_NOT_ENOUGH_DATA = "not_enough_data"
PARSE_TRY_OTHERS = "try_others"


class Protocol:
    name: str = "?"

    #: bytes of prefix parse() needs before a PARSE_TRY_OTHERS is
    #: *definitive*. Protocols whose discriminator sits deep in the header
    #: (nshead's magic at offset 24, mongo's opcode at 12) disclaim short
    #: prefixes only tentatively; the InputMessenger then waits for more
    #: bytes instead of failing the connection when nothing else claims a
    #: TCP-segmented frame (reference nshead returns NOT_ENOUGH_DATA here).
    min_probe_bytes: int = 0

    def parse(self, portal, socket) -> Tuple[str, object]:
        raise NotImplementedError

    def process(self, msg, socket):
        raise NotImplementedError

    def process_inline(self, msg, socket) -> bool:
        """Order-critical cheap dispatch in parse order (stream frames:
        enqueue to the per-stream ExecutionQueue and return True). The
        InputMessenger calls this for every message before considering
        fiber fan-out; returning False routes to process()."""
        return False


_protocols: List[Protocol] = []
_lock = threading.Lock()
_init_lock = threading.Lock()


_builtins_done = False


def register_protocol(p: Protocol) -> None:
    with _lock:
        if any(x.name == p.name for x in _protocols):
            return
        _protocols.append(p)


def get_protocols() -> List[Protocol]:
    global _builtins_done
    if not _builtins_done:
        # _lock is not reentrant and _register_builtins calls
        # register_protocol, so guard with a dedicated init lock
        with _init_lock:
            if not _builtins_done:
                _register_builtins()
                _builtins_done = True
    return list(_protocols)


def find_protocol(name: str) -> Optional[Protocol]:
    for p in get_protocols():
        if p.name == name:
            return p
    return None


def _register_builtins() -> None:
    # register in preference order; redis is last since its inline-command
    # form only engages on connections that already spoke RESP
    from brpc_tpu.protocol import (
        tpu_std, http, h2, thrift, nshead, esp, mongo, rtmp, redis, memcache,
        pbrpc_variants)
    tpu_std.ensure_registered()
    pbrpc_variants.ensure_registered()
    http.ensure_registered()
    h2.ensure_registered()
    thrift.ensure_registered()
    nshead.ensure_registered()
    esp.ensure_registered()
    mongo.ensure_registered()
    rtmp.ensure_registered()       # claims 0x03-version first bytes
    redis.ensure_registered()
    memcache.ensure_registered()   # client-only: TRY_OTHERS on servers
