"""HTTP/1.1 protocol: curl-able observability + JSON access to services
(policy/http_rpc_protocol.cpp + builtin/* — SURVEY.md §2.5, §2.7).

Server side:
  GET  /            index of builtin pages
  GET  /status      server + per-method stats        (StatusService)
  GET  /vars[?prefix=] exposed bvars                 (VarsService)
  GET  /flags       runtime flags; POST /flags/<name>?setvalue=v mutates
  GET  /health      liveness                         (HealthService)
  GET  /connections live connections                 (ConnectionsService)
  GET  /brpc_metrics prometheus text                 (PrometheusMetrics)
  GET  /rpcz[?trace_id=] recent spans                (RpczService)
  POST /<Service>/<Method>  JSON (pb methods) or raw-byte body -> RPC

The parser is peek-based like every protocol here: TRY_OTHERS unless the
bytes start with an HTTP method. pb messages render via protobuf's
json_format (the reference's json2pb bridge)."""

from __future__ import annotations

import json
import time
import urllib.parse
from typing import Optional, Tuple

from brpc_tpu.butil.flags import flag, list_flags, set_flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)

_METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"OPTIONS ",
            b"PATCH ")
_MAX_HEADER = 64 * 1024


_FC = False          # unresolved sentinel (None is a valid answer)


def _fastcore():
    """The extension, or None — also None for a stale prebuilt .so that
    predates the http symbols (the loader's fallback contract must hold
    per-symbol, not just per-module). Memoized: the answer cannot
    change within a process."""
    global _FC
    if _FC is False:
        from brpc_tpu.native import fastcore
        m = fastcore.get()
        _FC = m if m is not None and hasattr(m, "http_parse_request") \
            else None
    return _FC


class HttpRequest:
    __slots__ = ("method", "path", "query", "headers", "body", "keep_alive")

    def __init__(self, method, path, query, headers, body, keep_alive):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.keep_alive = keep_alive


def _response(status: int, body: bytes, content_type: str = "text/plain",
              keep_alive: bool = True) -> IOBuf:
    reason = {200: "OK", 400: "Bad Request", 403: "Forbidden",
              404: "Not Found", 405: "Method Not Allowed",
              500: "Internal Server Error"}.get(status, "OK")
    head = (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n"
            f"\r\n").encode()
    out = IOBuf()
    out.append(head)
    out.append(body)
    return out


def _shard_param(agg, req: "HttpRequest"):
    """Parse ?shard=i against the aggregator: (index|None, error|None)
    — None index means 'merged view'; a malformed or out-of-range value
    is a client error, not a silent fallback to merged."""
    raw = req.query.get("shard")
    if raw is None:
        return None, None
    try:
        i = int(raw)
    except ValueError:
        return None, (400, "text/plain", f"bad shard {raw!r}".encode())
    if not 0 <= i < agg.num_shards:
        return None, (400, "text/plain",
                      f"shard {i} out of range 0.."
                      f"{agg.num_shards - 1}".encode())
    return i, None


def _query_flag(req: "HttpRequest", name: str) -> bool:
    """Boolean query param: ?x=1 / ?x=true are on; ?x=0 / ?x=false are
    off (a raw truthy-string check would treat \"0\" as on). Bare keys
    (?x with no value) are dropped by the query parser — spell the
    value out."""
    v = req.query.get(name)
    if v is None:
        return False
    return v.lower() in ("1", "true", "yes")


def _trace_id_candidates(tid: str) -> set:
    """Both readings of a trace id: spans dump ids as 016x hex, but
    operators paste decimal from logs just as often — "123456" is
    ambiguous, so /rpcz matches EITHER reading (a 64-bit random id
    virtually never collides with its other-base twin)."""
    out = set()
    try:
        out.add(int(tid, 16))
    except ValueError:
        pass
    if tid.isdigit():
        out.add(int(tid, 10))
    return out


def _thread_stacks() -> bytes:
    """All OS threads' Python stacks (the /bthreads + /threads pages of
    the reference — here workers ARE pthreads running fibers)."""
    import sys
    import traceback
    frames = sys._current_frames()
    names = {t.ident: t.name for t in __import__("threading").enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---\n")
        out.extend(traceback.format_stack(frame))
        out.append("\n")
    return "".join(out).encode()


class HttpProtocol(Protocol):
    name = "http"

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        head = portal.peek_bytes(min(8, portal.size))
        if not any(m.startswith(head[:len(m)]) if len(head) < len(m)
                   else head.startswith(m) for m in _METHODS):
            return PARSE_TRY_OTHERS, None
        raw = portal.peek_bytes(min(portal.size, _MAX_HEADER))
        # fast lane: one native pass for head-find + start line + header
        # dict (httpparse.cc — the reference's C http_parser role,
        # details/http_parser.cpp). DEFER (-2) means "only CPython
        # semantics can judge these bytes": fall to the classic parser,
        # so the lanes cannot diverge (differential fuzz:
        # tests/test_http_native.py).
        parsed = None
        ext = _fastcore()
        if ext is not None:
            r = ext.http_parse_request(raw, _MAX_HEADER,
                                       flag("max_body_size"))
            if r is None:
                return PARSE_NOT_ENOUGH_DATA, None
            if isinstance(r, tuple):
                parsed = r
            elif r == -1:
                return PARSE_TRY_OTHERS, None
            # r == -2: defer to the classic lane below
        if parsed is None:
            sep = raw.find(b"\r\n\r\n")
            if sep < 0:
                if portal.size >= _MAX_HEADER:
                    return PARSE_TRY_OTHERS, None  # header flood: drop conn
                return PARSE_NOT_ENOUGH_DATA, None
            header_bytes = raw[:sep]
            lines = header_bytes.split(b"\r\n")
            try:
                method, target, _version = \
                    lines[0].decode("latin1").split(" ", 2)
            except ValueError:
                return PARSE_TRY_OTHERS, None
            headers = {}
            for line in lines[1:]:
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            try:
                body_len = int(headers.get("content-length", "0") or "0")
            except ValueError:
                return PARSE_TRY_OTHERS, None  # malformed: drop connection
            if body_len < 0 or body_len > flag("max_body_size"):
                return PARSE_TRY_OTHERS, None
            keep_alive = \
                headers.get("connection", "keep-alive").lower() != "close"
            parsed = (sep + 4, method.upper(), target, body_len,
                      keep_alive, headers)
        # shared tail: both lanes produced the same normalized head
        header_len, method, target, body_len, keep_alive, headers = parsed
        if portal.size < header_len + body_len:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(header_len)
        body = portal.cut(body_len).to_bytes()
        split = urllib.parse.urlsplit(target)
        query = dict(urllib.parse.parse_qsl(split.query))
        return PARSE_OK, HttpRequest(method, split.path, query, headers,
                                     body, bool(keep_alive))

    # -------------------------------------------------------------- process
    def process_inline(self, req: HttpRequest, socket) -> bool:
        """HTTP/1.1 requires responses in request order: pipelined
        requests must NOT fan out to concurrent fibers (the
        InputMessenger default)."""
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        process_in_parse_order(socket, "http", req, self.process)
        return True

    async def process(self, req: HttpRequest, socket):
        server = socket.user_data.get("server")
        if server is None:
            socket.write(_response(500, b"no server bound", keep_alive=False))
            return
        try:
            status, ctype, body = await self._route(server, req, socket)
        except Exception as e:
            status, ctype, body = 500, "text/plain", f"error: {e}".encode()
        from brpc_tpu.rpc.progressive import ProgressiveAttachment
        if isinstance(body, ProgressiveAttachment):
            # chunked transfer: headers now, body as the handler feeds it
            conn_hdr = "keep-alive" if req.keep_alive else "close"
            head = (f"HTTP/1.1 {status} OK\r\n"
                    f"Content-Type: {body.content_type}\r\n"
                    f"Transfer-Encoding: chunked\r\n"
                    f"Connection: {conn_hdr}\r\n\r\n").encode()
            out = IOBuf()
            out.append(head)
            socket.write(out)
            body._bind(socket)
            # hold the per-connection drain here until the body completes:
            # a pipelined request behind us would otherwise interleave its
            # response into the open chunked stream
            await body.wait_finished()
            if not req.keep_alive and not socket.failed:
                socket.write(IOBuf(), on_done=lambda ok: socket.set_failed(
                    ConnectionError("http connection: close")))
            return
        if req.keep_alive:
            socket.write(_response(status, body, ctype, True))
        else:
            # close only after the response actually flushes — set_failed
            # right after write() would race the async keep_write fiber
            # and drop the response
            socket.write(
                _response(status, body, ctype, False),
                on_done=lambda ok: socket.set_failed(
                    ConnectionError("http connection: close")))

    # --------------------------------------------------------------- routes
    async def _route(self, server, req: HttpRequest, socket=None):
        from brpc_tpu.rpc.auth import AuthError, resolve_server_auth
        path = req.path.rstrip("/") or "/"
        authenticator = resolve_server_auth(server.options)
        if authenticator is not None and path != "/health":
            # the tpu_std auth gate must not have an HTTP side door: require
            # the credential (Authorization: Bearer ... or ?token=)
            # everywhere except liveness; verified once per connection
            ctx = socket.user_data.get("auth_context") if socket else None
            if ctx is None:
                header = req.headers.get("authorization", "")
                cred = header[7:] if header.startswith("Bearer ") else \
                    req.query.get("token", "")
                try:
                    ctx = authenticator.verify_credential(
                        cred, socket.remote_endpoint if socket else None)
                except AuthError as e:
                    return 403, "text/plain", (
                        str(e) or "authentication failed").encode()
                except Exception:
                    return 403, "text/plain", b"authentication failed"
                if socket is not None:
                    socket.user_data["auth_context"] = ctx
        if path == "/":
            return 200, "text/html", self._index(server)
        if path == "/health":
            reporter = getattr(server.options, "health_reporter", None)
            if reporter is not None:
                # health_reporter.h: the app decides what healthy means
                try:
                    r = reporter(server)
                except Exception as e:
                    return 500, "text/plain", f"health reporter: {e}".encode()
                if isinstance(r, tuple):
                    status, ctype, body = r
                    body = body if isinstance(body, bytes) else str(body).encode()
                    return status, ctype, body
                return 200, "text/plain", (
                    r if isinstance(r, bytes) else str(r).encode())
            return 200, "text/plain", b"OK"
        # shard-group supervisor: /status, /vars and the prometheus dump
        # serve the MERGED view over the per-shard stores; ?shard=i
        # narrows any of them to one worker's snapshot
        agg = getattr(server, "shard_aggregator", None)
        if path == "/status":
            if agg is not None:
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None:
                        return (404, "text/plain",
                                f"no dump for shard {shard}".encode())
                    view = dict(dump.get("status", {}))
                    view.update(shard=dump.get("shard"),
                                pid=dump.get("pid"))
                    return 200, "application/json", json.dumps(
                        view, default=str).encode()
                return 200, "application/json", json.dumps(
                    agg.merged_status(), default=str).encode()
            return 200, "application/json", self._status(server)
        if path == "/vars" or path.startswith("/vars/"):
            from brpc_tpu.bvar.variable import dump_exposed
            prefix = req.query.get("prefix", path[6:] if len(path) > 6 else "")
            sname = req.query.get("series")
            if sname is not None:
                # ?series=<name>: that one variable's trend rings as
                # JSON (the /timeline data, scoped to one var — what
                # the inline sparkline links to). Unknown name = 400.
                if agg is not None:
                    merged = agg.merged_timeline(names=[sname])
                    ser = merged.get("series", {}).get(sname)
                else:
                    from brpc_tpu.bvar.series import global_series
                    ser = global_series().dump_series(
                        names=[sname]).get(sname)
                if ser is None:
                    return (400, "text/plain",
                            f"no series for {sname!r}".encode())
                return 200, "application/json", json.dumps(
                    {sname: ser}, default=str).encode()
            if agg is not None:
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None:
                        return (404, "text/plain",
                                f"no dump for shard {shard}".encode())
                    items = sorted((n, v)
                                   for n, v in dump.get("vars", {}).items()
                                   if n.startswith(prefix))
                else:
                    items = sorted(agg.merged_vars(prefix).items())
                lines = [f"{n} : {v}" for n, v in items]
            else:
                items = dump_exposed(prefix)
                # inline sparklines: the last minute's trend next to
                # each instant value (only names with a warm ring —
                # merged/shard views stay sparkline-free, their values
                # come from dumps, not the local rings)
                from brpc_tpu.bvar.series import (global_series,
                                                  series_enabled)
                col = global_series() if series_enabled() else None
                lines = []
                for n, v in items:
                    spark = col.spark(n) if col is not None else ""
                    lines.append(f"{n} : {v}  {spark}" if spark
                                 else f"{n} : {v}")
            return 200, "text/plain", ("\n".join(lines) + "\n").encode()
        if path == "/timeline":
            from brpc_tpu.builtin.services import timeline_page_payload
            names = req.query.get("name") or req.query.get("names")
            names = [n for n in names.split(",") if n] if names else None
            tprefix = req.query.get("prefix", "")
            if agg is not None:
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None or not dump.get("timeline"):
                        return (404, "text/plain",
                                f"no timeline for shard {shard}"
                                .encode())
                    payload = dict(dump["timeline"])
                    if names or tprefix:
                        payload["series"] = {
                            k: v for k, v in
                            (payload.get("series") or {}).items()
                            if (names is None or k in names)
                            and k.startswith(tprefix)}
                else:
                    payload = agg.merged_timeline(names=names,
                                                  prefix=tprefix)
            else:
                payload = timeline_page_payload(server, names=names,
                                                prefix=tprefix)
            if names:
                missing = [n for n in names
                           if n not in payload.get("series", {})]
                if missing:
                    return (400, "text/plain",
                            f"no series for {missing[0]!r}".encode())
            return 200, "application/json", json.dumps(
                payload, default=str).encode()
        if path == "/brpc_metrics" or path == "/metrics":
            from brpc_tpu.bvar.prometheus import dump_prometheus
            if agg is not None:
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    from brpc_tpu.bvar.prometheus import (
                        dump_prometheus_items)
                    dump = agg.shard_dump(shard)
                    if dump is None:
                        return (404, "text/plain",
                                f"no dump for shard {shard}".encode())
                    return 200, "text/plain", dump_prometheus_items(
                        sorted(dump.get("vars", {}).items())).encode()
                return 200, "text/plain", agg.prometheus_text().encode()
            return 200, "text/plain", dump_prometheus().encode()
        if path == "/shards":
            if agg is None:
                return (404, "text/plain",
                        b"not a shard-group supervisor")
            out = {"shards": agg.num_shards,
                   "heartbeat_age_s": {
                       str(i): agg.heartbeat_age_s(i)
                       for i in range(agg.num_shards)}}
            if agg.group is not None:
                out["group"] = agg.group.group_status()
            return 200, "application/json", json.dumps(
                out, default=str).encode()
        if path == "/flags" or path.startswith("/flags/"):
            return self._flags(req, path)
        if path == "/connections":
            from brpc_tpu.builtin.services import connections_page
            return 200, "application/json", json.dumps(
                connections_page(server), default=str).encode()
        if path == "/backends":
            # per-backend CLIENT telemetry: this process's channels,
            # one row per (channel, backend) stat cell — the data
            # tools/cluster_top.py scrapes and pools across nodes
            from brpc_tpu.rpc.backend_stats import backends_page_payload
            return 200, "application/json", json.dumps(
                backends_page_payload(), default=str).encode()
        if path == "/serving":
            from brpc_tpu.serving.service import serving_page_payload
            if agg is not None:
                # supervisor: merge the shard engines' payloads
                # (counters sum, histograms merge); ?shard=i narrows
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None or not dump.get("serving"):
                        return (404, "text/plain",
                                f"no serving dump for shard {shard}"
                                .encode())
                    return 200, "application/json", json.dumps(
                        dump["serving"], default=str).encode()
                return 200, "application/json", json.dumps(
                    agg.merged_serving(), default=str).encode()
            return 200, "application/json", json.dumps(
                serving_page_payload(server), default=str).encode()
        if path == "/device":
            from brpc_tpu.transport.device_stats import device_page_payload
            if agg is not None:
                # supervisor: merge the shard device views (counters
                # sum, latency samples pool); ?shard=i narrows
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None or not dump.get("device"):
                        return (404, "text/plain",
                                f"no device dump for shard {shard}"
                                .encode())
                    return 200, "application/json", json.dumps(
                        dump["device"], default=str).encode()
                return 200, "application/json", json.dumps(
                    agg.merged_device(), default=str).encode()
            return 200, "application/json", json.dumps(
                device_page_payload(server), default=str).encode()
        if path == "/lb_trace":
            from brpc_tpu.rpc.backend_stats import lb_trace_payload
            try:
                n = max(1, int(req.query.get("n", "100")))
            except ValueError:
                return (400, "text/plain",
                        f"bad n {req.query.get('n')!r}".encode())
            name = req.query.get("channel")
            payload = lb_trace_payload(name, n)
            if payload is None:
                return (404, "text/plain",
                        f"no decision ring for channel {name!r}".encode())
            return 200, "application/json", json.dumps(
                payload, default=str).encode()
        if path == "/rpcz":
            from brpc_tpu.rpc.span import global_collector, global_store
            tid = req.query.get("trace_id")
            ids = None
            if tid:
                ids = _trace_id_candidates(tid)
                if not ids:
                    return (400, "text/plain",
                            f"bad trace_id {tid!r}".encode())
            try:
                n = max(1, int(req.query.get("n", "50")))
            except ValueError:
                return (400, "text/plain",
                        f"bad n {req.query.get('n')!r}".encode())
            if _query_flag(req, "history"):
                # read back from the on-disk SpanDB analog (rpcz_dir):
                # spans that aged out of the in-memory ring
                rows = global_store.read(n, trace_id=ids)
                return 200, "application/json", json.dumps(rows).encode()
            if ids:
                spans = global_collector.find_trace(ids)
            else:
                spans = global_collector.recent(n)
            return 200, "application/json", json.dumps(
                [s.to_dict() for s in spans]).encode()
        if path == "/list":
            # service/method enumeration with message types
            # (builtin/list_service.cpp)
            out = {}
            for name, s in server.services().items():
                out[name] = {
                    m.name: {
                        "request_type": (m.request_class.__name__
                                         if m.request_class else "bytes"),
                        "response_type": (m.response_class.__name__
                                          if m.response_class else "bytes"),
                    } for m in s.methods.values()
                }
            return 200, "application/json", json.dumps(out).encode()
        if path == "/version":
            import jax
            from brpc_tpu import __version__
            return 200, "application/json", json.dumps({
                "brpc_tpu": __version__, "jax": jax.__version__,
                "server": "brpc-tpu"}).encode()
        if path == "/protobufs":
            return 200, "application/json", self._protobufs(server)
        if path == "/sockets":
            return 200, "application/json", self._sockets(server)
        if path == "/fibers" or path == "/bthreads":
            if _query_flag(req, "stacks"):
                from brpc_tpu.fiber.stacks import dump_fiber_stacks
                return 200, "text/plain", dump_fiber_stacks().encode()
            return 200, "application/json", self._fibers(server)
        if path == "/threads":
            return 200, "text/plain", _thread_stacks()
        if path == "/ids":
            from brpc_tpu.rpc.controller import _call_pool
            return 200, "application/json", json.dumps(
                {"inflight_client_calls": max(0, len(_call_pool) - 1)}
            ).encode()
        if path == "/hotspots" or path == "/pprof/profile":
            return await self._hotspots(req, agg=agg)
        if path == "/census":
            from brpc_tpu.builtin.services import census_page_payload
            if agg is not None:
                # supervisor: the group-wide census (per-shard payloads
                # ride the dumps; counts/bytes sum); ?shard=i narrows
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                if shard is not None:
                    dump = agg.shard_dump(shard)
                    if dump is None or not dump.get("census"):
                        return (404, "text/plain",
                                f"no census for shard {shard}".encode())
                    return 200, "application/json", json.dumps(
                        dump["census"], default=str).encode()
                return 200, "application/json", json.dumps(
                    agg.merged_census(), default=str).encode()
            return 200, "application/json", json.dumps(
                census_page_payload(server), default=str).encode()
        if path == "/capture":
            return self._capture(server, req, agg=agg)
        if path == "/incidents":
            return self._incidents(server, req, agg=agg)
        if path == "/contentions":
            from brpc_tpu.fiber.contention import contention_report
            rows = contention_report(int(req.query.get("n", "30")))
            lines = ["count  total_wait_us  site\n"] + [
                f"{c:6d} {w:13.1f}  {site}\n" for site, c, w in rows]
            return 200, "text/plain", "".join(lines).encode()
        if path == "/vlog":
            return self._vlog(req)
        # /Service/Method RPC access
        parts = [p for p in path.split("/") if p]
        if len(parts) == 2:
            return await self._call_method(server, req, parts[0], parts[1],
                                           socket)
        return 404, "text/plain", f"no such page {req.path}".encode()

    # ------------------------------------------------- introspection pages
    def _incidents(self, server, req: HttpRequest, agg=None):
        """/incidents: capture-on-anomaly state + artifact ledger
        (incident/manager.py), and the artifact download
        (?action=download&path=...). On a shard-group SUPERVISOR the
        state view merges per-shard incident sections (?shard=i
        narrows to one shard's dump) and downloads resolve against
        any shard's ledger."""
        from brpc_tpu.builtin.services import incidents_page_payload
        action = req.query.get("action", "")
        if action == "download":
            from brpc_tpu.incident.artifact import SUFFIX as _INC_SUFFIX
            path = req.query.get("path", "")
            if agg is not None:
                rows = agg.merged_incidents().get("artifacts") or []
            else:
                rows = incidents_page_payload(server).get(
                    "artifacts") or []
            known = {r.get("path") for r in rows}
            # ledger membership IS the authorization: an arbitrary
            # ?path= must not read arbitrary files
            if not path or path not in known \
                    or not path.endswith(_INC_SUFFIX):
                return 404, "text/plain", b"no such incident artifact"
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                return 404, "text/plain", b"artifact unreadable"
            return 200, "application/octet-stream", data
        if action:
            return (400, "text/plain",
                    f"unknown incidents action {action!r}".encode())
        if agg is not None:
            shard, err = _shard_param(agg, req)
            if err is not None:
                return err
            if shard is not None:
                dump = agg.shard_dump(shard)
                if dump is None or not dump.get("incidents"):
                    return (404, "text/plain",
                            f"no incidents for shard {shard}".encode())
                return 200, "application/json", json.dumps(
                    dump["incidents"], default=str).encode()
            return 200, "application/json", json.dumps(
                agg.merged_incidents(), default=str).encode()
        return 200, "application/json", json.dumps(
            incidents_page_payload(server), default=str).encode()

    def _capture(self, server, req: HttpRequest, agg=None):
        """/capture: traffic-recorder state, runtime control
        (?action=start&dir=...&rate=..., ?action=stop) and the merged
        corpus download (?action=download). On a shard-group
        SUPERVISOR, start/stop write the control file the shards apply
        on their next dump tick, the state view merges per-shard
        recorder snapshots, and the download merges every shard's
        per-pid corpus files into one arrival-ordered corpus."""
        from brpc_tpu.builtin.services import (capture_control,
                                               capture_download_bytes,
                                               capture_page_payload)
        action = req.query.get("action", "")
        if agg is not None:
            group = agg.group
            if action in ("start", "stop"):
                if group is None:
                    return (404, "text/plain",
                            b"no supervisor for capture control")
                seq = group.write_capture_control(action, dict(req.query))
                return 200, "application/json", json.dumps(
                    {"control": action, "seq": seq,
                     "applied_within_s": group.options.dump_interval_s},
                    default=str).encode()
            if action == "download":
                data = capture_download_bytes(agg.capture_paths())
                if not data:
                    return 404, "text/plain", b"no captured corpus"
                return 200, "application/octet-stream", data
            if action:
                return (400, "text/plain",
                        f"unknown capture action {action!r}".encode())
            return 200, "application/json", json.dumps(
                agg.merged_capture(), default=str).encode()
        if action in ("start", "stop"):
            try:
                snap = capture_control(action, dict(req.query))
            except (ValueError, OSError) as e:
                return 400, "text/plain", str(e).encode()
            return 200, "application/json", json.dumps(
                snap, default=str).encode()
        if action == "download":
            data = capture_download_bytes()
            if not data:
                return 404, "text/plain", b"no captured corpus"
            return 200, "application/octet-stream", data
        if action:
            return (400, "text/plain",
                    f"unknown capture action {action!r}".encode())
        return 200, "application/json", json.dumps(
            capture_page_payload(server), default=str).encode()

    def _protobufs(self, server) -> bytes:
        out = {}
        for sname, svc in server.services().items():
            for mname, method in svc.methods.items():
                entry = {}
                for side, cls in (("request", method.request_class),
                                  ("response", method.response_class)):
                    if cls is None:
                        entry[side] = "bytes"
                    else:
                        desc = getattr(cls, "DESCRIPTOR", None)
                        entry[side] = desc.full_name if desc else cls.__name__
                        if desc is not None:
                            entry[f"{side}_fields"] = sorted(
                                f.name for f in desc.fields)
                out[f"{sname}.{mname}"] = entry
        return json.dumps(out, indent=1).encode()

    def _sockets(self, server) -> bytes:
        rows = []
        for s in server.connections():
            rows.append({
                "id": s.id,
                "remote": str(s.remote_endpoint) if s.remote_endpoint else None,
                "local": str(s.local_endpoint) if s.local_endpoint else None,
                "failed": s.failed,
                "fail_reason": str(getattr(s, "fail_reason", "") or ""),
                "write_queue": (s._wq.depth()
                                if getattr(s, "_wq", None) is not None else 0),
                "write_queue_bytes": getattr(s, "wq_bytes", 0),
                "preferred_protocol": s.preferred_protocol,
            })
            # device-lane introspection for ici:// conns (the page the
            # RDMA build exposes per-endpoint window state on)
            conn = s.conn
            if hasattr(conn, "lane_kind"):
                rows[-1]["lane_kind"] = conn.lane_kind
                rows[-1]["outstanding_batches"] = conn.outstanding_batches
        return json.dumps(rows, indent=1).encode()

    def _fibers(self, server) -> bytes:
        c = server._control
        return json.dumps({
            "concurrency": c.concurrency,
            "alive_fibers": c.nfibers.get_value(),
            "fibers_created": c.nfibers_created.get_value(),
            "switches_per_group": {g.index: g.nswitches for g in c.groups},
            "steals_per_group": {g.index: g.nsteals for g in c.groups},
            "runqueue_depth": {
                g.index: len(g.rq) + len(g.remote_rq) + len(g.bound_rq)
                for g in c.groups},
        }).encode()

    async def _hotspots(self, req: HttpRequest, agg=None):
        from brpc_tpu.builtin.profiler import (
            growth_profile, heap_profile, heap_stop, render_flamegraph_svg,
            render_folded, render_text)
        from brpc_tpu.fiber.sync import FiberEvent
        ptype = req.query.get("type", "cpu")
        if ptype in ("heap", "growth"):
            # tracemalloc snapshots are quick; no sampler thread needed
            if _query_flag(req, "stop"):
                return 200, "text/plain", heap_stop().encode()
            try:
                top = min(200, int(req.query.get("top", "40")))
            except ValueError:
                return 400, "text/plain", b"bad top"
            text = (heap_profile(top) if ptype == "heap"
                    else growth_profile(top))
            return 200, "text/plain", text.encode()
        if ptype != "cpu":
            return 400, "text/plain", b"type must be cpu|heap|growth"
        fmt = req.query.get("format")
        from brpc_tpu.builtin import flight_recorder as fr
        if req.query.get("mode") == "continuous":
            # the always-on flight recorder: serve the windowed ring,
            # no sample wait. A shard-group SUPERVISOR merges the
            # per-shard recorder states from the dumps (counters sum —
            # the PR 5 aggregation discipline); ?shard=i narrows.
            if agg is not None:
                if _query_flag(req, "diff"):
                    return (400, "text/plain",
                            b"diff is per-process; use ?shard=i on a "
                            b"worker")
                shard, err = _shard_param(agg, req)
                if err is not None:
                    return err
                states = []
                dumps = [agg.shard_dump(shard)] if shard is not None \
                    else agg.read_dumps()
                for d in dumps:
                    if d and d.get("hotspots"):
                        states.append(d["hotspots"])
                m = fr.merge_dump_states(states)
            else:
                rec = fr.global_recorder()
                if _query_flag(req, "diff"):
                    return (200, "text/plain",
                            fr.render_diff_text(rec.window_diff()).encode())
                m = rec.merged()
                from brpc_tpu.transport.event_dispatcher import (
                    stall_ms_max_10s)
                m["stall_ms_max_10s"] = stall_ms_max_10s()
            if fmt == "folded":
                return 200, "text/plain", render_folded(
                    m["folded"]).encode()
            if fmt in ("svg", "flamegraph"):
                return (200, "image/svg+xml",
                        render_flamegraph_svg(m["folded"]).encode())
            if fmt == "json":
                return 200, "application/json", json.dumps({
                    "nsamples": m["nsamples"], "nbusy": m["nbusy"],
                    "windows": m.get("windows"),
                    "span_s": m.get("span_s"),
                    "stall_ms_max_10s": m.get("stall_ms_max_10s"),
                    "labels": dict(m["labels"]),
                    "folded": dict(m["folded"].most_common(200)),
                }).encode()
            return (200, "text/plain",
                    fr.render_continuous_text(m).encode())
        try:
            seconds = min(30.0, float(req.query.get("seconds", "1")))
        except ValueError:
            return 400, "text/plain", b"bad seconds"
        # on-demand profile: the sample loop runs on the flight
        # recorder's sampler thread; THIS handler fiber parks on an
        # event (a worker is never pinned for the sample window), and a
        # concurrent profile is refused with 503 instead of queueing —
        # one profile at a time, like the reference's /hotspots.
        done = FiberEvent()
        result: dict = {}

        def on_done(leaves, folded, n):
            result["v"] = (leaves, folded, n)
            done.set()

        rec = fr.global_recorder()
        if not rec.request_profile(seconds, 0.005, on_done):
            return (503, "text/plain",
                    b"another profile is already running")
        await done.wait(seconds + 30)
        if "v" not in result:
            return 503, "text/plain", b"profile did not complete"
        leaves, folded, n = result["v"]
        if fmt == "folded":
            return 200, "text/plain", render_folded(folded).encode()
        if fmt in ("svg", "flamegraph"):
            return (200, "image/svg+xml",
                    render_flamegraph_svg(folded).encode())
        return 200, "text/plain", render_text(leaves, n).encode()

    def _vlog(self, req: HttpRequest):
        import logging as pylog
        module = req.query.get("module", "")
        level = req.query.get("level")
        vmod = req.query.get("vmodule")
        if vmod is not None:
            # per-module VLOG verbosity (--vmodule): "pat=N,pat=N" or "N"
            from brpc_tpu.butil.logging import set_vmodule
            try:
                set_vmodule(vmod)
            except ValueError as e:
                return 400, "text/plain", f"bad vmodule: {e}".encode()
            return 200, "text/plain", b"OK"
        if level is not None:
            try:
                pylog.getLogger(module or None).setLevel(level.upper())
            except ValueError as e:
                return 400, "text/plain", f"bad level: {e}".encode()
            return 200, "text/plain", b"OK"
        from brpc_tpu.butil.logging import vmodule
        loggers = {"root": pylog.getLevelName(pylog.getLogger().level)}
        for name in sorted(pylog.root.manager.loggerDict):
            lg = pylog.root.manager.loggerDict[name]
            if isinstance(lg, pylog.Logger) and lg.level != pylog.NOTSET:
                loggers[name] = pylog.getLevelName(lg.level)
        return 200, "application/json", json.dumps(
            {"loggers": loggers, "vmodule": vmodule()}).encode()

    def _index(self, server) -> bytes:
        from brpc_tpu.builtin.tabbed import render_index
        return render_index(server)

    def _status(self, server) -> bytes:
        from brpc_tpu.builtin.services import status_page
        return json.dumps(status_page(server), default=str).encode()

    def _flags(self, req: HttpRequest, path: str):
        if path.startswith("/flags/") and ("setvalue" in req.query
                                           or req.method == "POST"):
            name = path[len("/flags/"):]
            value = req.query.get("setvalue", req.body.decode() or "")
            if set_flag(name, value):
                return 200, "text/plain", b"OK"
            return 400, "text/plain", f"cannot set flag {name!r}".encode()
        rows = [f"{n} = {v!r} (default {d!r})  # {h}"
                for n, v, d, h in list_flags()]
        return 200, "text/plain", ("\n".join(rows) + "\n").encode()

    async def _call_method(self, server, req: HttpRequest, service: str,
                           method_name: str, socket=None):
        method = server.find_method(service, method_name)
        if method is None:
            return 404, "text/plain", b"no such service/method"
        from brpc_tpu.rpc.controller import Controller
        cntl = Controller()
        cntl.remote_side = socket.remote_endpoint if socket else None
        cntl._service_name = service
        cntl._method_name = method_name
        if socket is not None:
            cntl.auth_context = socket.user_data.get("auth_context")
        if method.request_class is not None:
            from google.protobuf import json_format
            request = method.request_class()
            if req.body:
                try:
                    json_format.Parse(req.body.decode(), request)
                except Exception as e:
                    return 400, "text/plain", f"bad json: {e}".encode()
        else:
            request = req.body
        # cost rides to on_request_end: weighted limiter slots must
        # release what they charged (rpc/admission.CostModel)
        cost = server.on_request_start(f"{service}.{method_name}",
                                       len(req.body or b""))
        if not cost:
            return 500, "text/plain", b"max_concurrency reached"
        interceptor = getattr(server.options, "interceptor", None)
        if interceptor is not None:
            from brpc_tpu.rpc.auth import InterceptorError
            try:
                verdict = interceptor(cntl)
            except InterceptorError as e:
                verdict = (e.error_code, e.reason)
            except Exception as e:
                verdict = (500, f"interceptor error: {e}")
            if verdict is not None:
                server.on_request_end(f"{service}.{method_name}", 0,
                                      True, cost)
                return 403, "text/plain", str(verdict[1]).encode()
        t0 = time.monotonic_ns()
        try:
            import inspect
            r = method.handler(cntl, request)
            if inspect.isawaitable(r):
                r = await r  # we run inside the dispatch fiber
            response = r
        except Exception as e:
            server.on_request_end(f"{service}.{method_name}",
                                  (time.monotonic_ns() - t0) / 1e3, True,
                                  cost)
            return 500, "text/plain", f"handler error: {e}".encode()
        server.on_request_end(f"{service}.{method_name}",
                              (time.monotonic_ns() - t0) / 1e3,
                              cntl.failed(), cost)
        if cntl.failed():
            # honor the cntl.set_failed error pattern over HTTP too
            from brpc_tpu.rpc import errno_codes as berr
            status = 400 if cntl.error_code == berr.EREQUEST else 500
            return (status, "text/plain",
                    f"[{cntl.error_code}] {cntl.error_text}".encode())
        if cntl._progressive is not None:
            # body arrives in chunks after the handler (progressive
            # attachment); process() writes the chunked headers
            return 200, cntl._progressive.content_type, cntl._progressive
        if response is None:
            return 200, "application/json", b"{}"
        if hasattr(response, "SerializeToString") and not isinstance(
                response, (bytes, bytearray)):
            from google.protobuf import json_format
            return (200, "application/json",
                    json_format.MessageToJson(response).encode())
        if isinstance(response, IOBuf):
            return 200, "application/octet-stream", response.to_bytes()
        return 200, "application/octet-stream", bytes(response)


_instance: Optional[HttpProtocol] = None


def ensure_registered() -> HttpProtocol:
    global _instance
    if _instance is None:
        _instance = HttpProtocol()
        register_protocol(_instance)
    return _instance
