"""nshead protocol (baidu legacy family): 36-byte little-endian header +
raw body (policy/nshead_protocol.cpp, nshead_service.h in the
reference; the nshead struct is public baidu infra:
id/version/log_id/provider[16]/magic/reserved/body_len, magic
0xfb709394).

Server side: ServerOptions.nshead_service — a handler
``(socket, NsheadMessage) -> NsheadMessage | bytes | None`` (None = no
reply, matching NsheadService's manual-response mode). Client:
NsheadClient with FIFO matching (nshead has no correlation field; the
reference matches by connection order too)."""

from __future__ import annotations

import inspect
import struct
import time
from typing import List, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.pipelined import PipelinedClient

NSHEAD_MAGIC = 0xFB709394
_HDR = struct.Struct("<HHI16sIII")
HEADER_SIZE = 36
_MAX_BODY = 64 << 20


class NsheadMessage:
    __slots__ = ("id", "version", "log_id", "provider", "body", "reserved")

    def __init__(self, body: bytes = b"", id: int = 0, version: int = 0,
                 log_id: int = 0, provider: bytes = b"brpc-tpu",
                 reserved: int = 0):
        self.id = id
        self.version = version
        self.log_id = log_id
        self.provider = provider[:16]
        self.body = bytes(body)
        # nova_pbrpc carries the method index here
        # (nova_pbrpc_protocol.cpp ParseNsheadMeta)
        self.reserved = reserved

    def pack(self) -> bytes:
        return _HDR.pack(self.id, self.version, self.log_id,
                         self.provider.ljust(16, b"\x00"), NSHEAD_MAGIC,
                         self.reserved, len(self.body)) + self.body


def unpack_head(head: bytes) -> Tuple[int, int, int, bytes, int, int, int]:
    return _HDR.unpack(head)


class NsheadProtocol(Protocol):
    name = "nshead"
    min_probe_bytes = 28   # magic lives at offset 24: shorter prefixes
    #                        cannot be definitively disclaimed

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        head = portal.peek_bytes(min(HEADER_SIZE, portal.size))
        if len(head) < 28:
            # magic lives at offset 24; until visible we can only bail on
            # impossible prefixes via the magic bytes themselves
            return PARSE_TRY_OTHERS, None
        magic = struct.unpack_from("<I", head, 24)[0]
        if magic != NSHEAD_MAGIC:
            return PARSE_TRY_OTHERS, None
        if len(head) < HEADER_SIZE:
            return PARSE_NOT_ENOUGH_DATA, None
        id_, version, log_id, provider, _magic, reserved, body_len = \
            _HDR.unpack(head)
        if body_len > _MAX_BODY:
            socket.set_failed(ConnectionError(
                f"nshead body of {body_len} bytes exceeds max"))
            return PARSE_NOT_ENOUGH_DATA, None
        if portal.size < HEADER_SIZE + body_len:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(HEADER_SIZE)
        body = portal.cut(body_len).to_bytes()
        msg = NsheadMessage(body, id_, version, log_id,
                            provider.rstrip(b"\x00"), reserved=reserved)
        return PARSE_OK, msg

    # -------------------------------------------------------------- process
    def process_inline(self, msg: NsheadMessage, socket) -> bool:
        client = socket.user_data.get("nshead_client")
        if client is not None:
            client._on_reply(socket, msg)
            return True
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        process_in_parse_order(socket, "nshead", msg, self._run_handler)
        return True

    async def _run_handler(self, msg: NsheadMessage, socket):
        server = socket.user_data.get("server")
        handler = (getattr(server.options, "nshead_service", None)
                   if server is not None else None)
        if handler is None:
            # no adaptor: echo the head with an empty body, erring visibly
            out = IOBuf()
            out.append(NsheadMessage(b"", msg.id, msg.version,
                                     msg.log_id).pack())
            socket.write(out)
            return
        cost = server.on_request_start("nshead.process")
        if not cost:
            return
        t0 = time.monotonic_ns()
        error = False
        reply = None
        try:
            r = handler(socket, msg)
            if inspect.isawaitable(r):
                r = await r
            reply = r
        except Exception:
            error = True
        server.on_request_end("nshead.process",
                              (time.monotonic_ns() - t0) / 1e3, error, cost)
        if reply is None:
            return
        if isinstance(reply, (bytes, bytearray, memoryview)):
            # raw-bytes replies do NOT inherit the request's version:
            # version bits are adaptor-specific flags (e.g. nova's
            # snappy bit) and echoing them would mark this uncompressed
            # body as compressed at the peer — adaptors that need header
            # control return a full NsheadMessage instead
            reply = NsheadMessage(bytes(reply), msg.id, 0, msg.log_id)
        out = IOBuf()
        out.append(reply.pack())
        socket.write(out)

    def process(self, msg, socket):
        raise AssertionError("nshead messages are processed inline")


class NsheadClient(PipelinedClient):
    user_data_key = "nshead_client"

    def __init__(self, address: str | EndPoint, timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        super().__init__(address, ensure_registered(), timeout_s=timeout_s,
                         control=control)

    def call(self, msg: NsheadMessage | bytes) -> NsheadMessage:
        if isinstance(msg, (bytes, bytearray, memoryview)):
            msg = NsheadMessage(bytes(msg))
        batch = self._start(msg.pack(), 1)
        return self._wait(batch, "nshead call")[0]

    async def call_async(self, msg: NsheadMessage | bytes) -> NsheadMessage:
        if isinstance(msg, (bytes, bytearray, memoryview)):
            msg = NsheadMessage(bytes(msg))
        batch = self._start(msg.pack(), 1)
        return (await self._wait_async(batch, "nshead call"))[0]


_instance: Optional[NsheadProtocol] = None


def ensure_registered() -> NsheadProtocol:
    global _instance
    if _instance is None:
        _instance = NsheadProtocol()
        register_protocol(_instance)
    return _instance
