"""tpu_std: the native protocol — fixed header + proto meta + payload +
attachment, a re-design of baidu_std framing
(policy/baidu_rpc_protocol.cpp: ParseRpcMessage:95, PackRpcRequest:646,
ProcessRpcRequest:314, ProcessRpcResponse:565).

Wire layout:
    "TRPC" | body_size:u32be | meta_size:u32be | meta | payload | attachment
body_size = meta_size + len(payload) + len(attachment).

Device payloads do NOT serialize into the byte stream on device-capable
transports: meta.device_payloads describes them and the arrays ride the
socket's device lane (write_device_payload / take_device_payload) — the
tpu analogue of RDMA SGEs pointing into registered blocks. On plain byte
transports they are inlined into the attachment (inline_bytes=true).
"""

from __future__ import annotations

import struct
import time
from typing import List, Optional, Tuple

import numpy as np

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)

MAGIC = b"TRPC"
HEADER_SIZE = 12
_HDR = struct.Struct(">4sII")

# ---------------------------------------------------------- small-call pack
# Hand-encoded protobuf fields for the per-call variable part of RpcMeta.
# The constant part (request submessage: service/method/timeout/auth) is
# serialized ONCE per channel+method and cached; per call we append only
# the correlation_id (field 4, varint) and attachment_size (field 5,
# varint) — wire-identical to a full SerializeToString, at bytes-concat
# cost. The reference pays a full meta pack per call in C++
# (PackRpcRequest, baidu_rpc_protocol.cpp:646); in Python the pb object
# build is the hot cost, so the fast path removes it entirely.
_TAG_CORRELATION_ID = 0x20   # field 4, wire type 0
_TAG_ATTACHMENT_SIZE = 0x28  # field 5, wire type 0

# frames at/under this total size take the single-bytes fast path on BOTH
# wire ends (channel request pack / server response pack); bigger frames
# stay zero-copy IOBuf chains — the fast path's attachment flatten +
# one-allocation assembly would COPY them
SMALL_FRAME_MAX = 32768
# scan_frames additionally admits complete live-stream DATA frames up
# to THIS size (its max_stream_body arg; both lanes pass it). 0 = off:
# the record's payload slice is a memcpy, while the classic path moves
# large payloads as zero-copy IOBuf refs that consumers which only
# size/forward never flatten — measured at parity-to-slightly-worse on
# 256KB frames here (box noise bounds the comparison). Scan admission
# pays off for small frames, where the pb-parse saving dominates.
STREAM_SCAN_MAX = 0


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes((b | 0x80,))
        else:
            return out + bytes((b,))


def _py_pack_small_frame(meta_prefix: bytes, cid: int, payload: bytes,
                         attachment: bytes = b"",
                         magic: bytes = MAGIC) -> bytes:
    meta = meta_prefix + _TAG_CORRELATION_ID.to_bytes(1, "big") + _varint(cid)
    if attachment:
        meta += _TAG_ATTACHMENT_SIZE.to_bytes(1, "big") + _varint(len(attachment))
    meta_size = len(meta)
    body = meta_size + len(payload) + len(attachment)
    return b"".join((_HDR.pack(magic, body, meta_size), meta, payload,
                     attachment))


# the fastcore extension resolves on FIRST USE, not import (get() may
# compile it — imports must stay cheap); False = not yet resolved
_fc = False


def _resolve_fc():
    global _fc
    from brpc_tpu.native import fastcore as _fastcore
    _fc = _fastcore.get()
    return _fc


def pack_small_frame(meta_prefix: bytes, cid: int, payload: bytes,
                     attachment: bytes = b"",
                     magic: bytes = MAGIC) -> bytes:
    """One-allocation frame assembly for the small-call fast path:
    native (fastcore.cc pack_frame — header + cached meta prefix +
    hand-encoded varint fields + payload + attachment in one memcpy
    pass, no pb object, no IOBuf) with a bit-identical Python twin."""
    fc = _fc
    if fc is False:
        fc = _resolve_fc()
    if fc is not None:
        return fc.pack_frame(magic, meta_prefix, cid, payload, attachment)
    return _py_pack_small_frame(meta_prefix, cid, payload, attachment, magic)


def _py_pack_frame_head(meta_prefix: bytes, cid: int, att_size: int,
                        tail_len: int, magic: bytes = MAGIC) -> bytes:
    meta = meta_prefix + _TAG_CORRELATION_ID.to_bytes(1, "big") + _varint(cid)
    if att_size:
        meta += _TAG_ATTACHMENT_SIZE.to_bytes(1, "big") + _varint(att_size)
    return _HDR.pack(magic, len(meta) + tail_len + att_size,
                     len(meta)) + meta


def pack_frame_head(meta_prefix: bytes, cid: int, att_size: int,
                    tail_len: int, magic: bytes = MAGIC) -> bytes:
    """Header + meta scratch for a BIG frame whose payload/attachment
    ride behind it as zero-copy IOBuf refs (fastcore.cc
    pack_frame_head; bit-identical Python twin). body_size covers
    meta + tail_len + att_size — the caller appends exactly those
    bytes. Kills the per-call prefix+varint byte joins on the
    big-attachment request path and the cut-through response head."""
    fc = _fc
    if fc is False:
        fc = _resolve_fc()
    fn = getattr(fc, "pack_frame_head", None) if fc is not None else None
    if fn is not None:
        return fn(magic, meta_prefix, cid, att_size, tail_len)
    return _py_pack_frame_head(meta_prefix, cid, att_size, tail_len, magic)


class RpcMessage:
    """One parsed tpu_std message."""

    __slots__ = ("meta", "payload", "attachment", "device_arrays",
                 "arrival_ns", "device_recv")

    def __init__(self, meta: pb.RpcMeta, payload: IOBuf, attachment: IOBuf,
                 device_arrays: Optional[List] = None):
        self.meta = meta
        self.payload = payload
        self.attachment = attachment
        self.device_arrays = device_arrays or []
        # device-lane recv info (peer/lane/recv_us) stamped by the
        # socket's take_device_payload — dispatch hangs a device-recv
        # child span off the server span from it
        self.device_recv = None
        # cut-time stamp: the server-side deadline budget (request
        # timeout_ms) counts from HERE, so dispatch queueing — a burst
        # fanned out to fibers behind busy workers — spends the budget
        # (the reference stamps received_us in InputMessenger the same
        # way; pre-cut kernel/portal buffering is invisible to both)
        self.arrival_ns = time.monotonic_ns()


def serialize_payload(obj) -> bytes:
    """Shared request/response serialization ladder: bytes-likes pass
    through, IOBufs flatten, protobuf messages serialize."""
    if obj is None:
        return b""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj)
    if isinstance(obj, IOBuf):
        return obj.to_bytes()
    ser = getattr(obj, "SerializeToString", None)
    if ser is not None:
        return ser()
    raise TypeError(f"cannot serialize payload of type {type(obj)!r}")


def pack_message(meta: pb.RpcMeta, payload: bytes | IOBuf,
                 attachment: Optional[IOBuf] = None,
                 device_arrays: Optional[List] = None,
                 device_lane: bool = False,
                 magic: bytes = MAGIC) -> Tuple[IOBuf, Optional[List]]:
    """Encode a frame. Returns (wire_iobuf, device_arrays_for_lane|None).

    device_arrays: jax/numpy arrays. With device_lane they stay out of the
    byte stream; otherwise their bytes are appended to the attachment.
    """
    user_attachment = attachment if attachment is not None else IOBuf()
    lane = None
    attachment = IOBuf()
    if device_arrays:
        del meta.device_payloads[:]
        for arr in device_arrays:
            dp = meta.device_payloads.add()
            dp.dtype = str(arr.dtype)
            dp.shape.extend(int(s) for s in arr.shape)
            dp.inline_bytes = not device_lane
            nbytes = int(np.prod(arr.shape or (1,))) * arr.dtype.itemsize
            dp.nbytes = nbytes
            if not device_lane:
                host = np.asarray(arr)
                attachment.append(host.tobytes())
        if device_lane:
            lane = list(device_arrays)
    # layout: inline device bytes FIRST, then the user attachment — the
    # receiver front-cuts dp.nbytes per inline payload and what remains is
    # the user attachment (unpack_inline_device_arrays)
    attachment.append_buf(user_attachment)
    meta.attachment_size = len(attachment)
    meta_bytes = meta.SerializeToString()
    if isinstance(payload, IOBuf):
        payload_buf = payload
    else:
        payload_buf = IOBuf()
        payload_buf.append(payload)
    body_size = len(meta_bytes) + payload_buf.size + attachment.size
    out = IOBuf()
    out.append(_HDR.pack(magic, body_size, len(meta_bytes)))
    out.append(meta_bytes)
    out.append_buf(payload_buf)
    out.append_buf(attachment)
    return out, lane


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def unpack_inline_device_arrays(msg: RpcMessage) -> List:
    """Materialize inline device payloads from the attachment bytes."""
    out = []
    buf = msg.attachment
    for dp in msg.meta.device_payloads:
        if dp.inline_bytes:
            raw = buf.cut(dp.nbytes).to_bytes()
            arr = np.frombuffer(raw, dtype=_np_dtype(dp.dtype)).reshape(tuple(dp.shape))
            out.append(arr)
        else:
            out.append(None)  # filled from the device lane by the caller
    return out


class TpuStdProtocol(Protocol):
    name = "tpu_std"
    MAGIC = MAGIC          # subclass variants (hulu/sofa pbrpc) re-magic it
    _scan_fn = False       # scan_frames resolved on first turbo_scan
    _serve_fn = False      # serve_scan resolved on first native_serve

    def frame(self, meta, payload, attachment=None, device_arrays=None,
              device_lane=False):
        """Wire framing for this protocol family; Channel and the server
        dispatch call this so replies match the request's framing."""
        return pack_message(meta, payload, attachment=attachment,
                            device_arrays=device_arrays,
                            device_lane=device_lane, magic=self.MAGIC)

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        # fast path: header (and usually the whole meta) sits in the
        # portal's contiguous head block — one native probe (fastcore.cc
        # parse_head) replaces peek copies + struct.unpack + slicing
        win = portal.first_host_view()
        meta_bytes = None
        body_size = None
        fc = _fc
        if fc is False:
            fc = _resolve_fc()
        if win is not None and fc is not None:
            r = fc.parse_head(win, self.MAGIC)
            if r == -1:
                # a magic/header mismatch is definitive even on a short
                # window (the C probe compares the available prefix)
                return PARSE_TRY_OTHERS, None
            if r is not None:
                body_size, meta_size, meta_bytes = r
            # r is None: matching prefix shorter than a header — the
            # header may span blocks; decide against the full portal
        if body_size is None:
            if portal.size < HEADER_SIZE:
                head = portal.peek_bytes(min(4, portal.size))
                if self.MAGIC[:len(head)] != head:
                    return PARSE_TRY_OTHERS, None
                return PARSE_NOT_ENOUGH_DATA, None
            magic, body_size, meta_size = _HDR.unpack(
                portal.peek_bytes(HEADER_SIZE))
            if magic != self.MAGIC:
                return PARSE_TRY_OTHERS, None
            if meta_size > body_size:
                return PARSE_TRY_OTHERS, None
        if body_size > 16 << 20:
            # only rare giant frames pay the flag lookup; a body_size
            # beyond max_body_size would otherwise buffer unbounded
            # toward a u32 claim that may never arrive (the reference
            # checks the same limit in ParseRpcMessage)
            from brpc_tpu.butil.flags import flag as _flagf
            if body_size > _flagf("max_body_size"):
                socket.set_failed(ConnectionError(
                    f"frame body {body_size} exceeds max_body_size"))
                return PARSE_NOT_ENOUGH_DATA, None
        if portal.size < HEADER_SIZE + body_size:
            # let the input loop skip re-probing until the frame is here
            socket.input_need = HEADER_SIZE + body_size
            return PARSE_NOT_ENOUGH_DATA, None
        meta = pb.RpcMeta()
        if meta_bytes is not None:
            meta.ParseFromString(meta_bytes)
            portal.pop_front(HEADER_SIZE + meta_size)
        else:
            portal.pop_front(HEADER_SIZE)
            meta.ParseFromString(portal.cut(meta_size).to_bytes())
        att_size = meta.attachment_size
        if att_size < 0 or meta_size + att_size > body_size:
            # a lying attachment_size would eat the next frame's bytes and
            # desync the whole connection: fail it instead
            socket.set_failed(ConnectionError(
                f"frame attachment_size {att_size} exceeds body"))
            return PARSE_NOT_ENOUGH_DATA, None
        payload = portal.cut(body_size - meta_size - att_size)
        attachment = portal.cut(att_size) if att_size else IOBuf()
        device_arrays: List = []
        device_recv = None
        if meta.device_payloads and any(not dp.inline_bytes
                                        for dp in meta.device_payloads):
            lane, device_recv = socket.take_device_payload_with_recv()
            if lane is not None:
                device_arrays = list(lane)
        msg = RpcMessage(meta, payload, attachment, device_arrays)
        msg.device_recv = device_recv
        return PARSE_OK, msg

    # ------------------------------------------------------- batch parse
    # frames above this body size take the classic per-frame path (their
    # payloads should stay zero-copy IOBuf refs, not batch copies)
    BATCH_MAX_BODY = 16384

    def batch_parse(self, portal, socket, max_frames: int = 64):
        """Native burst path: one ``bt_trpc_scan`` over the portal's
        contiguous head cuts every complete small frame at once,
        replacing per-message peek/unpack/cut iterations (the
        reference's ProcessNewMessage loop is C++ end to end).

        MEASURED HONESTLY (64-deep pipelined 4B echo, interleaved A/B):
        ~4.2k qps with this path vs ~4.4k without — the ctypes boundary
        plus per-frame Python assembly costs what the scan saves, since
        the per-frame header work it eliminates was already cheap
        (struct.unpack + upb protobuf are C). Default OFF via the
        ``tpu_std_batch_parse`` flag; kept as the wired, tested
        substrate a future C-API (non-ctypes) loop can extend.

        Returns a list of RpcMessage (never empty) when the fast path
        applied, else None — the caller falls back to parse(). Payload
        bytes are COPIED out of the window (small frames only), so the
        read block recycles safely."""
        from brpc_tpu.butil.flags import flag
        if not flag("tpu_std_batch_parse"):
            return None
        if self.MAGIC != MAGIC:
            # subclasses (hulu/sofa) inherit this method but the native
            # scanner only knows the TRPC magic — don't pay a doomed
            # scan + ValueError on every loop iteration for them
            return None
        from brpc_tpu import native
        win = portal.first_host_view()
        if win is None or len(win) < HEADER_SIZE:
            return None
        try:
            res = native.trpc_scan(win, max_frames)
        except ValueError:
            return None          # not (cleanly) TRPC: classic path decides
        if res is None:
            return None          # native lib unavailable
        frames, _consumed, _need = res
        if len(frames) < 2:
            return None          # no burst: classic path is just as fast
        msgs = []
        processed = 0
        for off, total in frames:
            body_size = total - HEADER_SIZE
            if body_size > self.BATCH_MAX_BODY:
                break            # big frame: classic zero-copy path
            meta_size = int.from_bytes(win[off + 8:off + 12], "big")
            meta = pb.RpcMeta()
            meta.ParseFromString(bytes(
                win[off + HEADER_SIZE:off + HEADER_SIZE + meta_size]))
            att_size = meta.attachment_size
            if att_size < 0 or meta_size + att_size > body_size:
                socket.set_failed(ConnectionError(
                    f"frame attachment_size {att_size} exceeds body"))
                break
            p0 = off + HEADER_SIZE + meta_size
            p1 = off + total - att_size
            payload = IOBuf()
            payload.append(bytes(win[p0:p1]))
            attachment = IOBuf()
            if att_size:
                attachment.append(bytes(win[p1:off + total]))
            device_arrays: List = []
            device_recv = None
            if meta.device_payloads and any(not dp.inline_bytes
                                            for dp in meta.device_payloads):
                lane, device_recv = \
                    socket.take_device_payload_with_recv()
                if lane is not None:
                    device_arrays = list(lane)
            m = RpcMessage(meta, payload, attachment, device_arrays)
            m.device_recv = device_recv
            msgs.append(m)
            processed = off + total
        if not msgs:
            return None
        portal.pop_front(processed)
        return msgs

    # --------------------------------------------------------- turbo lane
    def turbo_scan(self, portal, socket):
        """The native per-call loop's front half: ONE C call
        (fastcore.cc scan_frames) cuts every complete small fast frame
        out of the portal's contiguous head AND decodes the RpcMeta
        subset dispatch needs — replacing the per-message
        peek/parse_head/upb/cut span (the reference's compiled
        ProcessNewMessage + ParseRpcMessage loop,
        input_messenger.cpp:219-331). Returns dispatch records or None
        (fall back to the classic path). Payload/attachment bytes are
        sliced out before the portal pops, so read blocks recycle
        safely."""
        if type(self) is not TpuStdProtocol:
            return None      # re-magic'd variants keep classic semantics
        scan = self._scan_fn
        if scan is False:
            fc = _fc
            if fc is False:
                fc = _resolve_fc()
            # None when the extension is missing or prebuilt-stale —
            # including one too old for the materialize arg (probed
            # once here, not per drain)
            scan = getattr(fc, "scan_frames", None)
            if scan is not None:
                try:
                    scan(b"", MAGIC, 0, 0, 0, 1)
                except TypeError:
                    scan = None
            self._scan_fn = scan
        if scan is None:
            return None
        win = portal.first_host_view()
        if win is None or len(win) < HEADER_SIZE:
            return None
        # materialize=1: the whole batch's payload/attachment slices
        # happen inside the ONE native call — the records come back
        # dispatch-ready (no per-frame Python slicing), already in
        # turbo_dispatch's field order. Bytes are copied out before
        # the portal pops, so read blocks recycle safely.
        consumed, recs = scan(win, MAGIC, SMALL_FRAME_MAX, 128,
                              STREAM_SCAN_MAX, 1)
        if not recs:
            return None
        # cut-time stamp for the whole scanned run: records that defer
        # to the classic path (rpcz on, timeout-bearing metas) carry it
        # into the synthesized RpcMessage, so the server deadline budget
        # and the span's received_us anchor at the real frame cut
        socket.user_data["_turbo_cut_ns"] = time.monotonic_ns()
        portal.pop_front(consumed)
        return recs

    def native_serve(self, portal, socket) -> bool:
        """Serve the front run of small echo-class requests entirely in
        C (fastcore serve_scan): one native call parses, dispatches and
        prebuilds the response frames; one socket write sends them.
        Applies only to a server's ``native="echo"`` method under the
        same eligibility gates as the turbo lane. Returns True when a
        batch was served (caller loops)."""
        server = socket.user_data.get("server")
        if server is None:
            return False
        tgt = server._native_echo
        if tgt is None or type(self) is not TpuStdProtocol:
            return False
        serve = self._serve_fn
        if serve is False:
            fcm = _fc if _fc is not False else _resolve_fc()
            serve = self._serve_fn = getattr(fcm, "serve_scan", None)
        if serve is None:
            return False     # extension missing or prebuilt-stale
        global _turbo_ok, _flag, _cap_active
        if _turbo_ok is None:
            from brpc_tpu.butil.flags import flag as _flag
            from brpc_tpu.rpc.server_dispatch import (
                _server_turbo_ok as _turbo_ok,
                capture_active as _cap_active)
        if not _turbo_ok(server) or _flag("rpcz_enabled") \
                or _cap_active():
            # capture stands the all-C loop down: serve_scan never
            # crosses the interpreter, so it cannot record — requests
            # fall to the turbo/classic lanes, which capture in-line
            return False
        win = portal.first_host_view()
        if win is None or len(win) < HEADER_SIZE:
            return False
        t0 = time.monotonic_ns()
        consumed, out, n = serve(win, MAGIC, tgt[0], tgt[1],
                                 SMALL_FRAME_MAX)
        if not n:
            return False
        portal.pop_front(consumed)
        socket.write_small(out)
        server.account_native_batch(tgt[2], n,
                                    (time.monotonic_ns() - t0) / 1e3)
        return True

    # ------------------------------------------------------- cut-through
    def try_cut_through(self, portal, socket) -> bool:
        """Large-frame echo serving without assembly: when the portal's
        front is a (possibly partial) LARGE request frame addressed to
        the server's ``native="echo"`` method, the response header+meta
        go out as soon as the request meta parses, and the body forwards
        chunk-by-chunk as it arrives — zero-copy ref moves, every block
        still cache-hot when it leaves (the store-and-forward assembly
        an RPC server normally pays is what separates the raw
        stream-echo ceiling from the raw message-echo ceiling on this
        box). Classic cut-through switching; the reference's RDMA path
        gets the same effect from SGEs posted per block
        (rdma_endpoint.h:82 CutFromIOBufList).

        Frame-safety gate: only while NO other response can interleave
        (pending_responses == 0, no streams bound, write path idle
        frame-wise is guaranteed because responses and this forward all
        run in the input context). Returns True when cut-through mode
        was entered (state lives on the socket; the input loop forwards
        until drained)."""
        server = socket.user_data.get("server")
        if server is None:
            return False
        tgt = server._native_echo
        if tgt is None or type(self) is not TpuStdProtocol:
            return False
        if socket.pending_responses != 0 or \
                socket.user_data.get("bound_streams"):
            return False
        global _turbo_ok, _flag, _cap_active
        if _turbo_ok is None:
            from brpc_tpu.butil.flags import flag as _flag
            from brpc_tpu.rpc.server_dispatch import (
                _server_turbo_ok as _turbo_ok,
                capture_active as _cap_active)
        if not _turbo_ok(server) or _flag("rpcz_enabled") \
                or _cap_active() \
                or not _flag("tpu_std_cut_through"):
            return False
        if portal.size < HEADER_SIZE:
            return False
        magic, body_size, meta_size = _HDR.unpack(
            portal.peek_bytes(HEADER_SIZE))
        if magic != MAGIC or meta_size > body_size:
            return False
        if body_size <= SMALL_FRAME_MAX:
            return False         # small frames: serve_scan territory
        if body_size > 16 << 20:
            from brpc_tpu.butil.flags import flag as _f
            if body_size > _f("max_body_size"):
                return False     # classic path rejects it
        if portal.size < HEADER_SIZE + meta_size:
            return False         # wait for the full meta
        meta = pb.RpcMeta()
        try:
            meta.ParseFromString(
                portal.peek_bytes(HEADER_SIZE + meta_size)[HEADER_SIZE:])
        except Exception:
            return False
        req = meta.request
        if not meta.HasField("request") or meta.HasField("response") \
                or meta.HasField("stream_settings") or meta.device_payloads \
                or meta.compress_type or meta.trace_id \
                or req.auth_token \
                or req.service_name.encode() != tgt[0] \
                or req.method_name.encode() != tgt[1]:
            return False
        att = meta.attachment_size
        pa_len = body_size - meta_size           # payload + attachment
        if att < 0 or att > pa_len:
            return False         # lying size: classic path fails it
        portal.pop_front(HEADER_SIZE + meta_size)
        state = {"remaining": pa_len, "key": tgt[2],
                 "t0": time.monotonic_ns(), "server": server}
        socket.user_data["_cut_forward"] = state
        # response header+meta in ONE native allocation (no Python
        # varint joins), and header + already-arrived body leave in ONE
        # write (a separate header write is its own packet under
        # TCP_NODELAY — an extra syscall here and an extra wakeup on
        # the client)
        head = pack_frame_head(b"", meta.correlation_id, att, pa_len - att)
        self.cut_forward(portal, socket, state, prefix=head)
        return True

    def cut_forward(self, portal, socket, state, prefix=b"") -> bool:
        """Forward arrived body bytes out the response; True when the
        frame completed (mode exits)."""
        n = state["remaining"]
        if portal.size < n:
            n = portal.size
        if n or prefix:
            if n:
                out = portal.cut(n)              # zero-copy ref move
                if prefix:
                    head = IOBuf()
                    head.append(prefix)
                    head.append_buf(out)
                    out = head
            else:
                out = prefix
            socket.write(out)
            state["remaining"] -= n
        if state["remaining"] == 0:
            socket.user_data["_cut_forward"] = None
            state["server"].account_native_batch(
                state["key"], 1,
                (time.monotonic_ns() - state["t0"]) / 1e3)
            return True
        return False

    def turbo_dispatch(self, recs, socket):
        """Dispatch turbo_scan records in parse order; returns an
        optional pending coroutine (a classic-path fallback tail) under
        the same contract as process()."""
        from brpc_tpu.rpc.client_dispatch import process_response_fast
        from brpc_tpu.rpc.server_dispatch import process_request_fast
        from brpc_tpu.rpc.stream import process_stream_frame_fast
        server = socket.user_data.get("server")
        pending = []
        last = len(recs) - 1
        cut_ns = socket.user_data.get("_turbo_cut_ns", 0)
        for i, rec in enumerate(recs):
            if rec[0] == 1:
                process_response_fast(rec[1], rec[2], rec[3], rec[4],
                                      rec[5], socket)
            elif rec[0] == 2:
                # stream frames are order-critical: dispatched here in
                # parse order, like the classic process_inline path
                process_stream_frame_fast(rec[1], rec[2], rec[3],
                                          rec[4], rec[5], rec[6])
            else:
                r = process_request_fast(self, socket, server, rec[1],
                                         rec[2], rec[3], rec[4], rec[5],
                                         rec[6], is_last=(i == last),
                                         arrival_ns=cut_ns)
                if r is not None:
                    pending.append(r)
        if not pending:
            return None
        # same discipline as the classic loop: earlier fallbacks get
        # fresh fibers (under pending_responses claims, so the
        # cut-through gate sees them before any fiber starts) with ONE
        # amortized wake for the whole spill, the last runs in place
        from brpc_tpu.transport.input_messenger import counted_spawn_many
        if len(pending) > 1:
            counted_spawn_many(socket._control, socket, pending[:-1],
                               "process_tpu_std")
        return pending[-1]

    # -------------------------------------------------------------- process
    def process(self, msg: RpcMessage, socket):
        # dispatch to server/client/stream side, like ProcessRpcRequest /
        # ProcessRpcResponse / the streaming_rpc policy; imported lazily to
        # keep layering acyclic
        if msg.meta.HasField("request"):
            from brpc_tpu.rpc.server_dispatch import process_request
            return process_request(self, msg, socket)
        else:
            # pure stream frames never reach here: process_inline consumes
            # them in parse order
            from brpc_tpu.rpc.client_dispatch import process_response
            return process_response(self, msg, socket)

    def process_inline(self, msg: RpcMessage, socket) -> bool:
        meta = msg.meta
        if (meta.HasField("stream_settings") and not meta.HasField("request")
                and not meta.HasField("response") and not meta.correlation_id):
            from brpc_tpu.rpc.stream import process_stream_frame
            process_stream_frame(msg, socket)
            return True
        return False


_turbo_ok = None    # lazily bound server_dispatch._server_turbo_ok
_flag = None        # lazily bound butil.flags.flag
_cap_active = None  # lazily bound server_dispatch.capture_active

_instance: Optional[TpuStdProtocol] = None


def ensure_registered() -> TpuStdProtocol:
    global _instance
    if _instance is None:
        _instance = TpuStdProtocol()
        register_protocol(_instance)
    return _instance
