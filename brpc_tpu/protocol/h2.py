"""HTTP/2 (RFC 7540) + gRPC protocol.

TPU-native counterpart of the reference's h2/gRPC support
(policy/http2_rpc_protocol.cpp, details/hpack.cpp, grpc.{h,cpp},
http2.cpp): a full h2 connection — preface, SETTINGS exchange, HPACK
header compression, stream multiplexing, both-direction flow control,
PING/GOAWAY/RST_STREAM — carrying two request families:

  * gRPC  (content-type: application/grpc*): unary calls into the same
    Service/method registry tpu_std dispatches to, with grpc-status /
    grpc-message / grpc-timeout mapping. Interops with stock grpcio.
  * plain HTTP over h2: routed through the HTTP/1.1 protocol's router,
    so every builtin observability page is h2-reachable.

Server side registers as a Protocol (preface-sniffing parse); client
side is GrpcChannel (gRPC) and Http2Client (plain HTTP request()),
both driving the same H2Session over a client socket. Frame processing is serialized on the socket's input fiber
(process_inline), so recv-side state needs no lock; the send side is
guarded by a per-session lock because handler fibers write responses
concurrently.
"""

from __future__ import annotations

import struct
import threading
import time
import urllib.parse
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.protocol.hpack import HpackDecoder, HpackEncoder, HpackError
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types
DATA, HEADERS, PRIORITY, RST_STREAM, SETTINGS, PUSH_PROMISE, PING, GOAWAY, \
    WINDOW_UPDATE, CONTINUATION = range(10)

# flags
FLAG_END_STREAM = 0x1     # DATA, HEADERS
FLAG_ACK = 0x1            # SETTINGS, PING
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# settings ids
S_HEADER_TABLE_SIZE = 1
S_ENABLE_PUSH = 2
S_MAX_CONCURRENT_STREAMS = 3
S_INITIAL_WINDOW_SIZE = 4
S_MAX_FRAME_SIZE = 5
S_MAX_HEADER_LIST_SIZE = 6

# h2 error codes (RFC 7540 §7)
NO_ERROR, PROTOCOL_ERROR, INTERNAL_ERROR, FLOW_CONTROL_ERROR, \
    SETTINGS_TIMEOUT, STREAM_CLOSED, FRAME_SIZE_ERROR, REFUSED_STREAM, \
    CANCEL, COMPRESSION_ERROR, CONNECT_ERROR, ENHANCE_YOUR_CALM, \
    INADEQUATE_SECURITY, HTTP_1_1_REQUIRED = range(14)

DEFAULT_WINDOW = 65535
DEFAULT_FRAME_SIZE = 16384
OUR_INITIAL_WINDOW = 1 << 20      # advertise 1MB stream windows
OUR_MAX_FRAME_SIZE = 16384

_HDR = struct.Struct(">HBBI")     # we pack len as 1+2 manually


def pack_frame(ftype: int, flags: int, stream_id: int,
               payload: bytes = b"") -> bytes:
    n = len(payload)
    return (bytes(((n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF, ftype,
                   flags)) + struct.pack(">I", stream_id & 0x7FFFFFFF)
            + payload)


class H2Error(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


class H2Stream:
    __slots__ = ("id", "session", "headers", "trailers", "data",
                 "recv_window", "send_window", "closed_local",
                 "closed_remote", "blocked", "on_complete", "on_headers",
                 "on_data", "user")

    def __init__(self, sid: int, session: "H2Session"):
        self.id = sid
        self.session = session
        self.headers: List[Tuple[str, str]] = []
        self.trailers: List[Tuple[str, str]] = []
        self.data = bytearray()
        self.recv_window = session.our_initial_window
        self.send_window = session.peer_initial_window
        self.closed_local = False
        self.closed_remote = False
        self.blocked: deque = deque()   # (bytes, end_stream) awaiting window
        self.on_complete: Optional[Callable] = None
        self.on_headers: Optional[Callable] = None
        self.on_data: Optional[Callable] = None   # progressive consumer
        self.user = None

    def header(self, name: str, default: str = "") -> str:
        for k, v in self.headers:
            if k == name:
                return v
        return default


class H2Session:
    """One h2 connection, either role. Recv path runs on the socket input
    fiber (ordered); send path takes `_lock`."""

    def __init__(self, socket, is_server: bool,
                 on_request: Optional[Callable] = None):
        self.socket = socket
        self.is_server = is_server
        self.on_request = on_request     # server: stream completed
        self._lock = threading.Lock()
        self.encoder = HpackEncoder()
        self.decoder = HpackDecoder()
        self.streams: Dict[int, H2Stream] = {}
        self.next_stream_id = 2 if is_server else 1
        self.our_initial_window = OUR_INITIAL_WINDOW
        self.peer_initial_window = DEFAULT_WINDOW
        self.peer_max_frame = DEFAULT_FRAME_SIZE
        self.conn_recv_window = DEFAULT_WINDOW
        self.conn_send_window = DEFAULT_WINDOW
        self.goaway_sent = False
        self.goaway_received = False
        self.last_peer_stream = 0
        self._hdr_accum: Optional[Tuple[int, int, bytearray]] = None
        self._settings_acked = False

    # ------------------------------------------------------------- sending
    def _write(self, data: bytes) -> None:
        buf = IOBuf()
        buf.append(data)
        self.socket.write(buf)

    def send_preface_and_settings(self) -> None:
        out = b"" if self.is_server else PREFACE
        out += pack_frame(SETTINGS, 0, 0, struct.pack(
            ">HIHIHI",
            S_INITIAL_WINDOW_SIZE, self.our_initial_window,
            S_MAX_FRAME_SIZE, OUR_MAX_FRAME_SIZE,
            S_MAX_CONCURRENT_STREAMS, 1024))
        # widen the connection window up front (never shrinks below 64KB)
        out += pack_frame(WINDOW_UPDATE, 0, 0,
                          struct.pack(">I", (1 << 24) - DEFAULT_WINDOW))
        self.conn_recv_window = 1 << 24
        with self._lock:
            self._write(out)

    def new_stream(self) -> H2Stream:
        with self._lock:
            sid = self.next_stream_id
            self.next_stream_id += 2
            st = H2Stream(sid, self)
            self.streams[sid] = st
            return st

    def send_headers(self, stream: H2Stream, headers: List[Tuple[str, str]],
                     end_stream: bool = False) -> None:
        with self._lock:
            block = self.encoder.encode(headers)
            flags = FLAG_END_HEADERS | (FLAG_END_STREAM if end_stream else 0)
            self._write(pack_frame(HEADERS, flags, stream.id, block))
            if end_stream:
                stream.closed_local = True
                self._maybe_gc(stream)

    def send_data(self, stream: H2Stream, data: bytes,
                  end_stream: bool = False) -> None:
        with self._lock:
            stream.blocked.append((bytes(data), end_stream))
            self._flush_stream(stream)

    def _flush_stream(self, stream: H2Stream) -> None:
        # under _lock; emit as much blocked data as windows allow
        while stream.blocked:
            data, end = stream.blocked[0]
            if data:
                room = min(self.conn_send_window, stream.send_window,
                           self.peer_max_frame)
                if room <= 0:
                    return
                chunk, rest = data[:room], data[room:]
                self.conn_send_window -= len(chunk)
                stream.send_window -= len(chunk)
                if rest:
                    stream.blocked[0] = (rest, end)
                    self._write(pack_frame(DATA, 0, stream.id, chunk))
                    continue
                stream.blocked.popleft()
                flags = FLAG_END_STREAM if end else 0
                self._write(pack_frame(DATA, flags, stream.id, chunk))
            else:
                stream.blocked.popleft()
                flags = FLAG_END_STREAM if end else 0
                self._write(pack_frame(DATA, flags, stream.id, b""))
            if end:
                stream.closed_local = True
                self._maybe_gc(stream)
                return

    def _flush_all(self) -> None:
        for st in list(self.streams.values()):
            if st.blocked:
                self._flush_stream(st)
                if self.conn_send_window <= 0:
                    return

    def send_trailers(self, stream: H2Stream,
                      trailers: List[Tuple[str, str]]) -> None:
        self.send_headers(stream, trailers, end_stream=True)

    def send_rst(self, stream_id: int, code: int) -> None:
        with self._lock:
            self._write(pack_frame(RST_STREAM, 0, stream_id,
                                   struct.pack(">I", code)))
            self.streams.pop(stream_id, None)

    def send_goaway(self, code: int = NO_ERROR, debug: bytes = b"") -> None:
        with self._lock:
            if self.goaway_sent:
                return
            self.goaway_sent = True
            self._write(pack_frame(GOAWAY, 0, 0, struct.pack(
                ">II", self.last_peer_stream, code) + debug))

    def ping(self, payload: bytes = b"\0" * 8) -> None:
        with self._lock:
            self._write(pack_frame(PING, 0, 0, payload[:8].ljust(8, b"\0")))

    def _maybe_gc(self, stream: H2Stream) -> None:
        if stream.closed_local and stream.closed_remote:
            self.streams.pop(stream.id, None)

    # ------------------------------------------------------------ receiving
    def feed_frame(self, ftype: int, flags: int, sid: int,
                   payload: bytes) -> None:
        """Runs on the socket input fiber, frames in wire order."""
        if self._hdr_accum is not None and ftype != CONTINUATION:
            raise H2Error(PROTOCOL_ERROR,
                          "expected CONTINUATION in header block")
        if ftype == DATA:
            self._on_data(flags, sid, payload)
        elif ftype == HEADERS:
            self._on_headers(flags, sid, payload)
        elif ftype == CONTINUATION:
            self._on_continuation(flags, sid, payload)
        elif ftype == SETTINGS:
            self._on_settings(flags, payload)
        elif ftype == WINDOW_UPDATE:
            self._on_window_update(sid, payload)
        elif ftype == RST_STREAM:
            st = self.streams.pop(sid, None)
            if st is not None and st.on_complete:
                code = struct.unpack(">I", payload[:4])[0] if len(payload) >= 4 else 0
                st.trailers.append(("grpc-status", "1"))
                st.trailers.append(("grpc-message", f"stream reset by peer (h2 error {code})"))
                st.on_complete(st)
        elif ftype == PING:
            if not flags & FLAG_ACK:
                with self._lock:
                    self._write(pack_frame(PING, FLAG_ACK, 0, payload[:8]))
        elif ftype == GOAWAY:
            self.goaway_received = True
        elif ftype in (PRIORITY, PUSH_PROMISE):
            pass      # PRIORITY ignored; we never enable push
        # unknown frame types are ignored per RFC 7540 §4.1

    @staticmethod
    def _strip_padding(flags: int, payload: bytes) -> bytes:
        if flags & FLAG_PADDED:
            if not payload:
                raise H2Error(PROTOCOL_ERROR, "empty padded frame")
            pad = payload[0]
            if pad >= len(payload):
                raise H2Error(PROTOCOL_ERROR, "padding exceeds frame")
            payload = payload[1:len(payload) - pad]
        return payload

    def _on_data(self, flags: int, sid: int, payload: bytes) -> None:
        consumed = len(payload)
        payload = self._strip_padding(flags, payload)
        st = self.streams.get(sid)
        self.conn_recv_window -= consumed
        refill = []
        if self.conn_recv_window < (1 << 23):
            refill.append(pack_frame(WINDOW_UPDATE, 0, 0, struct.pack(
                ">I", (1 << 24) - self.conn_recv_window)))
            self.conn_recv_window = 1 << 24
        if st is None:
            # closed/reset stream: still account connection flow control
            if refill:
                with self._lock:
                    self._write(b"".join(refill))
            return
        st.recv_window -= consumed
        if st.recv_window < self.our_initial_window // 2:
            refill.append(pack_frame(WINDOW_UPDATE, 0, sid, struct.pack(
                ">I", self.our_initial_window - st.recv_window)))
            st.recv_window = self.our_initial_window
        if refill:
            with self._lock:
                self._write(b"".join(refill))
        if st.on_data is not None:
            st.on_data(payload, bool(flags & FLAG_END_STREAM))
        else:
            st.data.extend(payload)
        if flags & FLAG_END_STREAM:
            self._remote_closed(st)

    def _on_headers(self, flags: int, sid: int, payload: bytes) -> None:
        payload = self._strip_padding(flags, payload)
        if flags & FLAG_PRIORITY:
            payload = payload[5:]
        if sid > self.last_peer_stream and (sid % 2 == 1) == self.is_server:
            self.last_peer_stream = sid
        if flags & FLAG_END_HEADERS:
            self._header_block_done(sid, flags, bytes(payload))
        else:
            self._hdr_accum = (sid, flags, bytearray(payload))

    def _on_continuation(self, flags: int, sid: int, payload: bytes) -> None:
        if self._hdr_accum is None or self._hdr_accum[0] != sid:
            raise H2Error(PROTOCOL_ERROR, "unexpected CONTINUATION")
        self._hdr_accum[2].extend(payload)
        if flags & FLAG_END_HEADERS:
            sid, first_flags, block = self._hdr_accum
            self._hdr_accum = None
            self._header_block_done(sid, first_flags, bytes(block))

    def _header_block_done(self, sid: int, flags: int, block: bytes) -> None:
        try:
            headers = self.decoder.decode(block)
        except HpackError as e:
            raise H2Error(COMPRESSION_ERROR, str(e))
        st = self.streams.get(sid)
        if st is None:
            if self.is_server:
                st = H2Stream(sid, self)
                self.streams[sid] = st
            else:
                return   # headers for a stream we already tore down
        if st.headers and not st.closed_remote:
            st.trailers = headers      # second HEADERS block = trailers
        else:
            st.headers = headers
            if st.on_headers:
                st.on_headers(st)
        if flags & FLAG_END_STREAM or (st.headers and st.trailers):
            self._remote_closed(st)

    def _remote_closed(self, st: H2Stream) -> None:
        if st.closed_remote:
            return
        st.closed_remote = True
        if self.is_server and self.on_request is not None:
            self.on_request(st)
        elif st.on_complete is not None:
            st.on_complete(st)
        self._maybe_gc(st)

    def _on_settings(self, flags: int, payload: bytes) -> None:
        if flags & FLAG_ACK:
            self._settings_acked = True
            return
        if len(payload) % 6:
            raise H2Error(FRAME_SIZE_ERROR, "bad SETTINGS size")
        for off in range(0, len(payload), 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == S_HEADER_TABLE_SIZE:
                self.encoder.set_max_table_size(value)
            elif ident == S_INITIAL_WINDOW_SIZE:
                if value > 0x7FFFFFFF:
                    raise H2Error(FLOW_CONTROL_ERROR, "window too large")
                delta = value - self.peer_initial_window
                self.peer_initial_window = value
                with self._lock:
                    for st in self.streams.values():
                        st.send_window += delta
                    if delta > 0:
                        self._flush_all()
            elif ident == S_MAX_FRAME_SIZE:
                if not 16384 <= value <= 16777215:
                    raise H2Error(PROTOCOL_ERROR, "bad MAX_FRAME_SIZE")
                self.peer_max_frame = value
        with self._lock:
            self._write(pack_frame(SETTINGS, FLAG_ACK, 0))

    def _on_window_update(self, sid: int, payload: bytes) -> None:
        if len(payload) != 4:
            raise H2Error(FRAME_SIZE_ERROR, "bad WINDOW_UPDATE")
        inc = struct.unpack(">I", payload)[0] & 0x7FFFFFFF
        if inc == 0:
            raise H2Error(PROTOCOL_ERROR, "zero WINDOW_UPDATE")
        with self._lock:
            if sid == 0:
                self.conn_send_window += inc
                self._flush_all()
            else:
                st = self.streams.get(sid)
                if st is not None:
                    st.send_window += inc
                    self._flush_stream(st)


# --------------------------------------------------------------- gRPC bits

# gRPC status codes (grpc.h GrpcStatus in the reference)
GRPC_OK = 0
GRPC_CANCELLED = 1
GRPC_UNKNOWN = 2
GRPC_INVALID_ARGUMENT = 3
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14

_TIMEOUT_UNITS = {"H": 3600.0, "M": 60.0, "S": 1.0, "m": 1e-3, "u": 1e-6,
                  "n": 1e-9}


def parse_grpc_timeout(value: str) -> Optional[float]:
    """grpc-timeout header -> seconds (grpc.cpp timeout mapping)."""
    if not value or value[-1] not in _TIMEOUT_UNITS:
        return None
    try:
        return int(value[:-1]) * _TIMEOUT_UNITS[value[-1]]
    except ValueError:
        return None


def format_grpc_timeout(seconds: float) -> str:
    us = max(1, int(seconds * 1e6))
    if us < 1e8:
        return f"{us}u"
    return f"{int(seconds * 1e3)}m"


def pack_grpc_message(data: bytes, compressed: bool = False) -> bytes:
    return struct.pack(">BI", 1 if compressed else 0, len(data)) + data


def unpack_grpc_messages(data: bytes) -> List[bytes]:
    out = []
    pos = 0
    while pos + 5 <= len(data):
        compressed, n = struct.unpack_from(">BI", data, pos)
        pos += 5
        if pos + n > len(data):
            raise ValueError("truncated grpc message")
        body = data[pos:pos + n]
        pos += n
        if compressed:
            import gzip
            body = gzip.decompress(body)
        out.append(bytes(body))
    if pos != len(data):
        raise ValueError("trailing bytes after grpc message")
    return out


def percent_encode(msg: str) -> str:
    return urllib.parse.quote(msg, safe=" !#$&'()*+,-./:;<=>?@[]^_`{|}~")


def percent_decode(msg: str) -> str:
    return urllib.parse.unquote(msg)


_ERRNO_TO_GRPC = None


def errno_to_grpc_status(code: int) -> int:
    global _ERRNO_TO_GRPC
    if _ERRNO_TO_GRPC is None:
        from brpc_tpu.rpc import errno_codes as berr
        _ERRNO_TO_GRPC = {
            0: GRPC_OK,
            berr.ENOMETHOD: GRPC_NOT_FOUND,
            berr.ENOSERVICE: GRPC_NOT_FOUND,
            berr.EREQUEST: GRPC_INVALID_ARGUMENT,
            berr.ERPCTIMEDOUT: GRPC_DEADLINE_EXCEEDED,
            berr.ELIMIT: GRPC_UNAVAILABLE,
            berr.ECANCELED: GRPC_CANCELLED,
        }
    return _ERRNO_TO_GRPC.get(code, GRPC_INTERNAL)


# ---------------------------------------------------------- server protocol

class _FrameMsg:
    __slots__ = ("ftype", "flags", "sid", "payload", "is_preface")

    def __init__(self, ftype, flags, sid, payload, is_preface=False):
        self.ftype = ftype
        self.flags = flags
        self.sid = sid
        self.payload = payload
        self.is_preface = is_preface


class H2ServerProtocol(Protocol):
    """Server-side h2: sniffs the client preface, then cuts frames and
    feeds the per-connection session in parse order."""

    name = "h2"

    def parse(self, portal, socket) -> Tuple[str, object]:
        started = socket.user_data.get("h2_started")
        if not started:
            head = portal.peek_bytes(min(len(PREFACE), portal.size))
            if not PREFACE.startswith(head[:len(PREFACE)]):
                return PARSE_TRY_OTHERS, None
            if portal.size < len(PREFACE):
                return PARSE_NOT_ENOUGH_DATA, None
            portal.pop_front(len(PREFACE))
            socket.user_data["h2_started"] = True
            return PARSE_OK, _FrameMsg(-1, 0, 0, b"", is_preface=True)
        if portal.size < 9:
            return PARSE_NOT_ENOUGH_DATA, None
        head = portal.peek_bytes(9)
        length = (head[0] << 16) | (head[1] << 8) | head[2]
        if length > OUR_MAX_FRAME_SIZE:
            # we advertised SETTINGS_MAX_FRAME_SIZE=16384: a bigger frame
            # is FRAME_SIZE_ERROR (RFC 7540 §4.2) — fail the connection
            # instead of buffering a peer-controlled 16MB frame
            socket.set_failed(ConnectionError(
                f"h2 frame of {length} bytes exceeds max_frame_size"))
            return PARSE_NOT_ENOUGH_DATA, None
        if portal.size < 9 + length:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(9)
        payload = portal.cut(length).to_bytes() if length else b""
        sid = struct.unpack(">I", head[5:9])[0] & 0x7FFFFFFF
        return PARSE_OK, _FrameMsg(head[3], head[4], sid, payload)

    def process_inline(self, msg: _FrameMsg, socket) -> bool:
        session: Optional[H2Session] = socket.user_data.get("h2_session")
        if msg.is_preface:
            session = H2Session(socket, is_server=True,
                                on_request=self._dispatch)
            socket.user_data["h2_session"] = session
            session.send_preface_and_settings()
            return True
        if session is None:
            socket.set_failed(ConnectionError("h2 frame before preface"))
            return True
        try:
            session.feed_frame(msg.ftype, msg.flags, msg.sid, msg.payload)
        except H2Error as e:
            session.send_goaway(e.code, str(e).encode())
            socket.set_failed(ConnectionError(f"h2: {e}"))
        return True

    def process(self, msg, socket):   # pragma: no cover - inline-only
        return None

    # ------------------------------------------------------------ dispatch
    def _dispatch(self, stream: H2Stream) -> None:
        """Stream fully received (runs on the input fiber): hand the
        request to a handler fiber so the connection keeps parsing."""
        session = stream.session
        socket = session.socket
        server = socket.user_data.get("server")
        if server is None:
            session.send_rst(stream.id, REFUSED_STREAM)
            return
        ctype = stream.header("content-type")
        if ctype.startswith("application/grpc"):
            socket._control.spawn(self._handle_grpc, server, stream,
                                  name="h2_grpc")
        else:
            socket._control.spawn(self._handle_http, server, stream,
                                  name="h2_http")

    async def _handle_grpc(self, server, stream: H2Stream):
        session = stream.session
        path = stream.header(":path")
        parts = [p for p in path.split("/") if p]
        resp_headers = [(":status", "200"),
                        ("content-type", "application/grpc")]
        if len(parts) != 2:
            session.send_headers(stream, resp_headers)
            session.send_trailers(stream, [
                ("grpc-status", str(GRPC_NOT_FOUND)),
                ("grpc-message", percent_encode(f"bad path {path}"))])
            return
        service, method_name = parts
        # gRPC paths are package-qualified; our registry may not be
        method = (server.find_method(service, method_name)
                  or server.find_method(service.rsplit(".", 1)[-1],
                                        method_name))
        if method is None:
            session.send_headers(stream, resp_headers)
            session.send_trailers(stream, [
                ("grpc-status", str(GRPC_NOT_FOUND)),
                ("grpc-message",
                 percent_encode(f"no method {service}/{method_name}"))])
            return
        from brpc_tpu.rpc.controller import Controller
        cntl = Controller()
        cntl.remote_side = stream.session.socket.remote_endpoint
        timeout = parse_grpc_timeout(stream.header("grpc-timeout"))
        if timeout is not None:
            cntl.timeout_ms = timeout * 1e3
        status, message, payload = GRPC_OK, "", b""
        try:
            msgs = unpack_grpc_messages(bytes(stream.data))
            raw = msgs[0] if msgs else b""
            if method.request_class is not None:
                request = method.request_class()
                request.ParseFromString(raw)
            else:
                request = raw
        except Exception as e:
            status, message = GRPC_INTERNAL, f"bad request: {e}"
            request = None
        if status == GRPC_OK:
            # cost rides to on_request_end: weighted limiter slots
            # (rpc/admission.CostModel) must release what they charged
            cost = server.on_request_start(f"{service}.{method_name}")
            if not cost:
                status, message = GRPC_UNAVAILABLE, "max_concurrency reached"
            else:
                t0 = time.monotonic_ns()
                try:
                    import inspect
                    r = method.handler(cntl, request)
                    if inspect.isawaitable(r):
                        r = await r
                    if r is None:
                        payload = b""
                    elif hasattr(r, "SerializeToString") and not isinstance(
                            r, (bytes, bytearray)):
                        payload = r.SerializeToString()
                    elif isinstance(r, IOBuf):
                        payload = r.to_bytes()
                    else:
                        payload = bytes(r)
                except Exception as e:
                    status, message = GRPC_INTERNAL, f"handler error: {e}"
                finally:
                    server.on_request_end(
                        f"{service}.{method_name}",
                        (time.monotonic_ns() - t0) / 1e3,
                        status != GRPC_OK or cntl.failed(), cost)
                if status == GRPC_OK and cntl.failed():
                    status = errno_to_grpc_status(cntl.error_code)
                    message = cntl.error_text
        session.send_headers(stream, resp_headers)
        if status == GRPC_OK:
            session.send_data(stream, pack_grpc_message(payload))
        trailers = [("grpc-status", str(status))]
        if message:
            trailers.append(("grpc-message", percent_encode(message)))
        session.send_trailers(stream, trailers)

    async def _handle_http(self, server, stream: H2Stream):
        """Plain HTTP over h2: reuse the HTTP/1.1 router so /status,
        /vars, /rpcz ... are h2-reachable."""
        from brpc_tpu.protocol.http import HttpRequest, ensure_registered
        http = ensure_registered()
        target = stream.header(":path", "/")
        parsed = urllib.parse.urlsplit(target)
        req = HttpRequest(
            stream.header(":method", "GET").upper(), parsed.path,
            dict(urllib.parse.parse_qsl(parsed.query)),
            {k: v for k, v in stream.headers if not k.startswith(":")},
            bytes(stream.data), True)
        session = stream.session
        try:
            status, ctype, body = await http._route(server, req)
        except Exception as e:
            status, ctype, body = 500, "text/plain", f"error: {e}".encode()
        session.send_headers(stream, [
            (":status", str(status)), ("content-type", ctype),
            ("content-length", str(len(body)))])
        session.send_data(stream, body, end_stream=True)


# ----------------------------------------------------------------- client

class GrpcCall:
    """One in-flight unary call; completion is a FiberEvent so plain
    threads block (wait) and fibers await (wait_async) without parking
    their worker thread."""

    def __init__(self):
        self._event = FiberEvent()
        self.status: int = GRPC_INTERNAL
        self.message: str = ""
        self.response: bytes = b""
        self.headers: List[Tuple[str, str]] = []

    def _complete(self, stream: H2Stream) -> None:
        trailers = stream.trailers or stream.headers
        status = msg = None
        for k, v in trailers:
            if k == "grpc-status":
                status = v
            elif k == "grpc-message":
                msg = v
        if status is None:
            for k, v in stream.headers:   # trailers-only response
                if k == "grpc-status":
                    status = v
                elif k == "grpc-message":
                    msg = v
        self.status = int(status) if status is not None else GRPC_INTERNAL
        self.message = percent_decode(msg) if msg else ""
        try:
            msgs = unpack_grpc_messages(bytes(stream.data))
            self.response = msgs[0] if msgs else b""
        except ValueError as e:
            if self.status == GRPC_OK:
                self.status = GRPC_INTERNAL
                self.message = str(e)
        self.headers = stream.headers
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait_pthread(timeout)

    async def wait_async(self, timeout: Optional[float] = None) -> bool:
        return await self._event.wait(timeout)

    def ok(self) -> bool:
        return self.status == GRPC_OK


class GrpcChannel:
    """Client stub speaking gRPC-over-h2 (the client half of
    policy/http2_rpc_protocol.cpp). Interops with stock gRPC servers."""

    def __init__(self, address: str, control=None):
        from brpc_tpu.butil.endpoint import str2endpoint
        from brpc_tpu.fiber import global_control
        if "://" not in address:
            address = "tcp://" + address
        self._endpoint = str2endpoint(address)
        self._control = control or global_control()
        self._lock = threading.Lock()
        self._socket = None
        self._session: Optional[H2Session] = None
        # calls in flight, failed wholesale when their socket dies (ONE
        # on_failed registered per socket in _connect — a per-call
        # registration would leak a closure per call on the shared socket)
        self._pending: set = set()

    def _connect(self) -> H2Session:
        # connect_dedup (rpc/channel.py): connect OUTSIDE the lock —
        # a blocking connect (SYN timeout, slow accept) held under
        # _lock would park every other caller's worker thread on the
        # lock — publish under it, exactly one winner, losers discarded
        # with the closed-concurrently recheck.
        from brpc_tpu.rpc.channel import connect_dedup
        from brpc_tpu.transport.socket import create_client_socket

        def make():
            return create_client_socket(self._endpoint,
                                        on_input=self._on_input,
                                        control=self._control)

        published = []

        def publish(sock):
            self._socket = sock
            self._session = H2Session(sock, is_server=False)
            self._session.send_preface_and_settings()
            published.append(sock)

        sock = connect_dedup(self._lock, lambda: self._socket,
                             publish, make)
        with self._lock:
            session = self._session
        if published and published[0] is sock:
            # ONLY the publisher registers — every _connect() call runs
            # this tail, and re-registering on the long-lived winner
            # socket would grow its callback list per RPC. Outside the
            # lock: on_failed fires synchronously if the socket is
            # already dead, and _fail_pending takes _lock.
            sock.on_failed(self._fail_pending)
        return session

    def _fail_pending(self, socket) -> None:
        with self._lock:
            mine = [c for c in self._pending
                    if getattr(c, "_socket", None) is socket]
            self._pending.difference_update(mine)
        for call in mine:
            if not call._event.is_set():
                call.status = GRPC_UNAVAILABLE
                call.message = "connection failed"
                call._event.set()

    def _on_input(self, socket) -> None:
        portal = socket.input_portal
        session = self._session
        if session is None or session.socket is not socket:
            # the first bytes can arrive before _connect publishes the
            # session; the lock orders us behind it
            with self._lock:
                session = self._session
            if session is None or session.socket is not socket:
                return
        while portal.size >= 9:
            head = portal.peek_bytes(9)
            length = (head[0] << 16) | (head[1] << 8) | head[2]
            if portal.size < 9 + length:
                return
            portal.pop_front(9)
            payload = portal.cut(length).to_bytes() if length else b""
            sid = struct.unpack(">I", head[5:9])[0] & 0x7FFFFFFF
            try:
                session.feed_frame(head[3], head[4], sid, payload)
            except H2Error as e:
                session.send_goaway(e.code, str(e).encode())
                socket.set_failed(ConnectionError(f"h2: {e}"))
                return

    def call(self, method_path: str, request, timeout: Optional[float] = 5.0,
             metadata: Optional[List[Tuple[str, str]]] = None,
             response_class=None) -> GrpcCall:
        """Unary call. `method_path` is "/package.Service/Method".
        BLOCKS the calling thread; fibers use call_async."""
        call, session, stream, wait_s = self._start(method_path, request,
                                                    timeout, metadata)
        if not call.wait(wait_s):
            self._expire(call, session, stream)
        return self._finish(call, response_class)

    async def call_async(self, method_path: str, request,
                         timeout: Optional[float] = 5.0,
                         metadata: Optional[List[Tuple[str, str]]] = None,
                         response_class=None) -> GrpcCall:
        """Fiber-friendly unary call: awaits completion instead of
        parking the worker thread. (Connection ESTABLISHMENT still uses
        a blocking connect — only the first call on a channel pays it,
        and never while holding the channel lock.)"""
        call, session, stream, wait_s = self._start(method_path, request,
                                                    timeout, metadata)
        if not await call.wait_async(wait_s):
            self._expire(call, session, stream)
        return self._finish(call, response_class)

    def _start(self, method_path, request, timeout, metadata):
        if hasattr(request, "SerializeToString"):
            payload = request.SerializeToString()
        else:
            payload = bytes(request or b"")
        session = self._connect()
        call = GrpcCall()
        stream = session.new_stream()
        with self._lock:
            call._socket = session.socket
            self._pending.add(call)

        def _done(stream_):
            call._complete(stream_)
            with self._lock:
                self._pending.discard(call)

        stream.on_complete = _done
        headers = [
            (":method", "POST"), (":scheme", "http"),
            (":path", method_path),
            (":authority", f"{self._endpoint.host}:{self._endpoint.port}"),
            ("content-type", "application/grpc"),
            ("user-agent", "brpc-tpu-grpc/1.0"),
            ("te", "trailers"),
        ]
        if timeout is not None:
            headers.append(("grpc-timeout", format_grpc_timeout(timeout)))
        for kv in metadata or []:
            headers.append(kv)
        session.send_headers(stream, headers)
        session.send_data(stream, pack_grpc_message(payload),
                          end_stream=True)
        # one place owns the grace policy: a second past the grpc
        # deadline for the server's own DEADLINE_EXCEEDED to arrive
        wait_s = timeout + 1.0 if timeout is not None else None
        return call, session, stream, wait_s

    def _expire(self, call, session, stream) -> None:
        call.status = GRPC_DEADLINE_EXCEEDED
        call.message = "deadline exceeded"
        call._event.set()
        with self._lock:
            self._pending.discard(call)
        session.send_rst(stream.id, CANCEL)


    @staticmethod
    def _finish(call, response_class):
        if response_class is not None and call.ok():
            resp = response_class()
            resp.ParseFromString(call.response)
            call.response = resp
        return call

    def close(self) -> None:
        with self._lock:
            session, socket = self._session, self._socket
            self._socket = None
            self._session = None
        # set_failed fires _fail_pending synchronously, which takes _lock:
        # must not hold it here
        if session is not None:
            session.send_goaway()
        if socket is not None and not socket.failed:
            socket.set_failed(ConnectionError("channel closed"))


class HttpResponse:
    """Plain-HTTP-over-h2 response: status/headers/body."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: List[Tuple[str, str]],
                 body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def header(self, name: str, default: str = "") -> str:
        for k, v in self.headers:
            if k.lower() == name.lower():
                return v
        return default


class Http2Client(GrpcChannel):
    """Plain HTTP over h2 on the same session machinery GrpcChannel
    drives (the client half of the reference's h2 support beyond gRPC,
    policy/http2_rpc_protocol.cpp): request() multiplexes ordinary
    GET/POST streams — builtin observability pages, RESTful services —
    over one h2 connection."""

    def request(self, method: str, path: str, body: bytes = b"",
                headers: Optional[List[Tuple[str, str]]] = None,
                timeout: Optional[float] = 10.0) -> HttpResponse:
        """Blocking plain-HTTP exchange; raises H2Error on transport
        failure or timeout."""
        session = self._connect()
        call = GrpcCall()            # reused as a generic completion slot
        stream = session.new_stream()
        with self._lock:
            call._socket = session.socket
            self._pending.add(call)

        def _done(stream_):
            # a peer RST_STREAM completes the stream with synthetic
            # grpc-status trailers (feed_frame's reset path) — that is
            # a transport failure, not a response
            rst = None
            for k, v in stream_.trailers:
                if k == "grpc-status" and v not in ("0", ""):
                    rst = v
                    break
            if rst is not None:
                # headers-before-reset would otherwise surface as a
                # 200 with a silently truncated body
                call.status = GRPC_UNAVAILABLE
                call.message = f"stream reset (grpc-status {rst})"
            else:
                call.headers = stream_.headers
                call.response = bytes(stream_.data)
                call.status = GRPC_OK
            call._event.set()
            with self._lock:
                self._pending.discard(call)

        stream.on_complete = _done
        hdrs = [
            (":method", method.upper()), (":scheme", "http"),
            (":path", path),
            (":authority", f"{self._endpoint.host}:{self._endpoint.port}"),
        ]
        for kv in headers or []:
            hdrs.append(kv)
        session.send_headers(stream, hdrs, end_stream=not body)
        if body:
            session.send_data(stream, body, end_stream=True)
        if not call.wait(timeout):
            with self._lock:
                self._pending.discard(call)
            session.send_rst(stream.id, CANCEL)
            raise H2Error(CANCEL, f"h2 request timed out after {timeout}s")
        if call.status != GRPC_OK:
            raise H2Error(INTERNAL_ERROR, call.message or "request failed")
        resp = HttpResponse(0, call.headers, call.response)
        try:
            resp.status = int(resp.header(":status", "0") or 0)
        except ValueError as e:
            raise H2Error(PROTOCOL_ERROR, f"malformed :status: {e}") from None
        return resp



_instance: Optional[H2ServerProtocol] = None


def ensure_registered() -> H2ServerProtocol:
    global _instance
    if _instance is None:
        _instance = H2ServerProtocol()
        register_protocol(_instance)
    return _instance
