"""HPACK (RFC 7541) header compression for the HTTP/2 protocol
(counterpart of brpc/details/hpack.{h,cpp} + hpack-static-table.h).

Full implementation: static + dynamic tables, integer/string primitives,
Huffman coding both directions. HUFFMAN_TABLE and STATIC_TABLE are the
normative constants from RFC 7541 Appendix B / Appendix A (identical in
every conforming implementation)."""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

# (code, bit_length) for symbols 0..256 (256 = EOS) — RFC 7541 Appendix B
HUFFMAN_TABLE: List[Tuple[int, int]] = [
    (0x1ff8,13),(0x7fffd8,23),(0xfffffe2,28),(0xfffffe3,28),(0xfffffe4,28),
    (0xfffffe5,28),(0xfffffe6,28),(0xfffffe7,28),(0xfffffe8,28),(0xffffea,24),
    (0x3ffffffc,30),(0xfffffe9,28),(0xfffffea,28),(0x3ffffffd,30),
    (0xfffffeb,28),(0xfffffec,28),(0xfffffed,28),(0xfffffee,28),(0xfffffef,28),
    (0xffffff0,28),(0xffffff1,28),(0xffffff2,28),(0x3ffffffe,30),(0xffffff3,28),
    (0xffffff4,28),(0xffffff5,28),(0xffffff6,28),(0xffffff7,28),(0xffffff8,28),
    (0xffffff9,28),(0xffffffa,28),(0xffffffb,28),(0x14,6),(0x3f8,10),(0x3f9,10),
    (0xffa,12),(0x1ff9,13),(0x15,6),(0xf8,8),(0x7fa,11),(0x3fa,10),(0x3fb,10),
    (0xf9,8),(0x7fb,11),(0xfa,8),(0x16,6),(0x17,6),(0x18,6),(0x0,5),(0x1,5),
    (0x2,5),(0x19,6),(0x1a,6),(0x1b,6),(0x1c,6),(0x1d,6),(0x1e,6),(0x1f,6),
    (0x5c,7),(0xfb,8),(0x7ffc,15),(0x20,6),(0xffb,12),(0x3fc,10),(0x1ffa,13),
    (0x21,6),(0x5d,7),(0x5e,7),(0x5f,7),(0x60,7),(0x61,7),(0x62,7),(0x63,7),
    (0x64,7),(0x65,7),(0x66,7),(0x67,7),(0x68,7),(0x69,7),(0x6a,7),(0x6b,7),
    (0x6c,7),(0x6d,7),(0x6e,7),(0x6f,7),(0x70,7),(0x71,7),(0x72,7),(0xfc,8),
    (0x73,7),(0xfd,8),(0x1ffb,13),(0x7fff0,19),(0x1ffc,13),(0x3ffc,14),(0x22,6),
    (0x7ffd,15),(0x3,5),(0x23,6),(0x4,5),(0x24,6),(0x5,5),(0x25,6),(0x26,6),
    (0x27,6),(0x6,5),(0x74,7),(0x75,7),(0x28,6),(0x29,6),(0x2a,6),(0x7,5),
    (0x2b,6),(0x76,7),(0x2c,6),(0x8,5),(0x9,5),(0x2d,6),(0x77,7),(0x78,7),
    (0x79,7),(0x7a,7),(0x7b,7),(0x7ffe,15),(0x7fc,11),(0x3ffd,14),(0x1ffd,13),
    (0xffffffc,28),(0xfffe6,20),(0x3fffd2,22),(0xfffe7,20),(0xfffe8,20),
    (0x3fffd3,22),(0x3fffd4,22),(0x3fffd5,22),(0x7fffd9,23),(0x3fffd6,22),
    (0x7fffda,23),(0x7fffdb,23),(0x7fffdc,23),(0x7fffdd,23),(0x7fffde,23),
    (0xffffeb,24),(0x7fffdf,23),(0xffffec,24),(0xffffed,24),(0x3fffd7,22),
    (0x7fffe0,23),(0xffffee,24),(0x7fffe1,23),(0x7fffe2,23),(0x7fffe3,23),
    (0x7fffe4,23),(0x1fffdc,21),(0x3fffd8,22),(0x7fffe5,23),(0x3fffd9,22),
    (0x7fffe6,23),(0x7fffe7,23),(0xffffef,24),(0x3fffda,22),(0x1fffdd,21),
    (0xfffe9,20),(0x3fffdb,22),(0x3fffdc,22),(0x7fffe8,23),(0x7fffe9,23),
    (0x1fffde,21),(0x7fffea,23),(0x3fffdd,22),(0x3fffde,22),(0xfffff0,24),
    (0x1fffdf,21),(0x3fffdf,22),(0x7fffeb,23),(0x7fffec,23),(0x1fffe0,21),
    (0x1fffe1,21),(0x3fffe0,22),(0x1fffe2,21),(0x7fffed,23),(0x3fffe1,22),
    (0x7fffee,23),(0x7fffef,23),(0xfffea,20),(0x3fffe2,22),(0x3fffe3,22),
    (0x3fffe4,22),(0x7ffff0,23),(0x3fffe5,22),(0x3fffe6,22),(0x7ffff1,23),
    (0x3ffffe0,26),(0x3ffffe1,26),(0xfffeb,20),(0x7fff1,19),(0x3fffe7,22),
    (0x7ffff2,23),(0x3fffe8,22),(0x1ffffec,25),(0x3ffffe2,26),(0x3ffffe3,26),
    (0x3ffffe4,26),(0x7ffffde,27),(0x7ffffdf,27),(0x3ffffe5,26),(0xfffff1,24),
    (0x1ffffed,25),(0x7fff2,19),(0x1fffe3,21),(0x3ffffe6,26),(0x7ffffe0,27),
    (0x7ffffe1,27),(0x3ffffe7,26),(0x7ffffe2,27),(0xfffff2,24),(0x1fffe4,21),
    (0x1fffe5,21),(0x3ffffe8,26),(0x3ffffe9,26),(0xffffffd,28),(0x7ffffe3,27),
    (0x7ffffe4,27),(0x7ffffe5,27),(0xfffec,20),(0xfffff3,24),(0xfffed,20),
    (0x1fffe6,21),(0x3fffe9,22),(0x1fffe7,21),(0x1fffe8,21),(0x7ffff3,23),
    (0x3fffea,22),(0x3fffeb,22),(0x1ffffee,25),(0x1ffffef,25),(0xfffff4,24),
    (0xfffff5,24),(0x3ffffea,26),(0x7ffff4,23),(0x3ffffeb,26),(0x7ffffe6,27),
    (0x3ffffec,26),(0x3ffffed,26),(0x7ffffe7,27),(0x7ffffe8,27),(0x7ffffe9,27),
    (0x7ffffea,27),(0x7ffffeb,27),(0xffffffe,28),(0x7ffffec,27),(0x7ffffed,27),
    (0x7ffffee,27),(0x7ffffef,27),(0x7fffff0,27),(0x3ffffee,26),(0x3fffffff,30),
]

# RFC 7541 Appendix A — the 61-entry static table
STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""),
    ("access-control-allow-origin", ""), ("age", ""), ("allow", ""),
    ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""),
    ("content-location", ""), ("content-range", ""), ("content-type", ""),
    ("cookie", ""), ("date", ""), ("etag", ""), ("expect", ""),
    ("expires", ""), ("from", ""), ("host", ""), ("if-match", ""),
    ("if-modified-since", ""), ("if-none-match", ""), ("if-range", ""),
    ("if-unmodified-since", ""), ("last-modified", ""), ("link", ""),
    ("location", ""), ("max-forwards", ""), ("proxy-authenticate", ""),
    ("proxy-authorization", ""), ("range", ""), ("referer", ""),
    ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""),
    ("via", ""), ("www-authenticate", ""),
]

_STATIC_FULL: Dict[Tuple[str, str], int] = {}
_STATIC_NAME: Dict[str, int] = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_FULL.setdefault((_n, _v), _i + 1)
    _STATIC_NAME.setdefault(_n, _i + 1)

EOS = 256
_ENTRY_OVERHEAD = 32  # RFC 7541 §4.1


class HpackError(Exception):
    pass


# ------------------------------------------------------------- primitives

def encode_integer(value: int, prefix_bits: int, flags: int = 0) -> bytearray:
    """RFC 7541 §5.1 integer with an N-bit prefix; `flags` are the bits
    above the prefix in the first octet."""
    limit = (1 << prefix_bits) - 1
    out = bytearray()
    if value < limit:
        out.append(flags | value)
        return out
    out.append(flags | limit)
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return out


def decode_integer(data, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HpackError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 62:
            raise HpackError("integer overflow")
        if not b & 0x80:
            return value, pos


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code, length = HUFFMAN_TABLE[byte]
        acc = (acc << length) | code
        nbits += length
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all ones)
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def _build_decode_tree():
    # binary trie as a flat list of [left, right, symbol]
    tree = [[-1, -1, -1]]
    for sym, (code, length) in enumerate(HUFFMAN_TABLE):
        node = 0
        for i in range(length - 1, -1, -1):
            bit = (code >> i) & 1
            nxt = tree[node][bit]
            if nxt == -1:
                tree.append([-1, -1, -1])
                nxt = len(tree) - 1
                tree[node][bit] = nxt
            node = nxt
        tree[node][2] = sym
    return tree


_DECODE_TREE = _build_decode_tree()


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    tree = _DECODE_TREE
    node = 0
    depth = 0  # bits consumed since last symbol (for padding validation)
    pad = 0    # those bits' values: must end up all-ones (EOS prefix)
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            node = tree[node][bit]
            depth += 1
            pad = (pad << 1) | bit
            if node == -1:
                raise HpackError("invalid huffman code")
            sym = tree[node][2]
            if sym >= 0:
                if sym == EOS:
                    raise HpackError("EOS in huffman string")
                out.append(sym)
                node = 0
                depth = 0
                pad = 0
    if depth > 7:
        raise HpackError("huffman padding too long")
    # RFC 7541 §5.2: padding must be the most-significant bits of EOS,
    # i.e. all ones — any 0 bit in it is a decoding error
    if pad != (1 << depth) - 1:
        raise HpackError("huffman padding is not an EOS prefix")
    return bytes(out)


def encode_string(s: bytes, huffman: bool = True) -> bytearray:
    if huffman:
        enc = huffman_encode(s)
        if len(enc) < len(s):
            out = encode_integer(len(enc), 7, 0x80)
            out.extend(enc)
            return out
    out = encode_integer(len(s), 7, 0x00)
    out.extend(s)
    return out


def decode_string(data, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_integer(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string literal")
    raw = bytes(data[pos:pos + length])
    pos += length
    return (huffman_decode(raw) if huff else raw), pos


# ----------------------------------------------------------- dynamic table

class _DynamicTable:
    """FIFO of (name, value) with RFC 7541 §4 size accounting. Index 1 is
    the most recently inserted entry (offset by 61 static slots at the
    call sites)."""

    def __init__(self, max_size: int = 4096):
        self.entries: deque = deque()
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def entry_size(name: str, value: str) -> int:
        return len(name.encode()) + len(value.encode()) + _ENTRY_OVERHEAD

    def add(self, name: str, value: str) -> None:
        need = self.entry_size(name, value)
        self._evict(self.max_size - need)
        if need <= self.max_size:
            self.entries.appendleft((name, value))
            self.size += need

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        self._evict(max_size)

    def _evict(self, budget: int) -> None:
        while self.entries and self.size > budget:
            n, v = self.entries.pop()
            self.size -= self.entry_size(n, v)

    def get(self, index: int) -> Tuple[str, str]:
        if 1 <= index <= len(self.entries):
            return self.entries[index - 1]
        raise HpackError(f"dynamic table index {index} out of range")


# ------------------------------------------------------------------ codec

class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynamicTable(max_table_size)
        self._settings_max = max_table_size

    def set_max_table_size(self, n: int) -> None:
        """Connection SETTINGS_HEADER_TABLE_SIZE change: the encoder must
        emit a table-size update <= n; enforce the ceiling here."""
        self._settings_max = n
        if self._table.max_size > n:
            self._table.resize(n)

    def _lookup(self, index: int) -> Tuple[str, str]:
        if index == 0:
            raise HpackError("index 0")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        return self._table.get(index - len(STATIC_TABLE))

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        headers: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:                       # indexed
                index, pos = decode_integer(data, pos, 7)
                headers.append(self._lookup(index))
            elif b & 0x40:                     # literal + incremental index
                index, pos = decode_integer(data, pos, 6)
                name = (self._lookup(index)[0] if index
                        else None)
                if name is None:
                    raw, pos = decode_string(data, pos)
                    name = raw.decode("latin1")
                raw, pos = decode_string(data, pos)
                value = raw.decode("latin1")
                self._table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:                     # dynamic table size update
                size, pos = decode_integer(data, pos, 5)
                if size > self._settings_max:
                    raise HpackError("table size update above SETTINGS cap")
                self._table.resize(size)
            else:                              # literal, no/never indexing
                index, pos = decode_integer(data, pos, 4)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    raw, pos = decode_string(data, pos)
                    name = raw.decode("latin1")
                raw, pos = decode_string(data, pos)
                headers.append((name, raw.decode("latin1")))
        return headers


class HpackEncoder:
    def __init__(self, max_table_size: int = 4096, huffman: bool = True):
        self._table = _DynamicTable(max_table_size)
        self._huffman = huffman
        self._pending_resize: Optional[int] = None

    def set_max_table_size(self, n: int) -> None:
        self._pending_resize = n

    def _find(self, name: str, value: str) -> Tuple[int, int]:
        """-> (full_index, name_index); 0 = not found."""
        full = _STATIC_FULL.get((name, value), 0)
        name_idx = _STATIC_NAME.get(name, 0)
        for i, (n, v) in enumerate(self._table.entries):
            if n == name:
                if v == value and not full:
                    full = len(STATIC_TABLE) + i + 1
                    break
                if not name_idx:
                    name_idx = len(STATIC_TABLE) + i + 1
        return full, name_idx

    def encode(self, headers: List[Tuple[str, str]],
               sensitive=()) -> bytes:
        out = bytearray()
        if self._pending_resize is not None:
            self._table.resize(self._pending_resize)
            out.extend(encode_integer(self._pending_resize, 5, 0x20))
            self._pending_resize = None
        for name, value in headers:
            name = name.lower()
            if name in sensitive:   # never-indexed literal (RFC 7541 §6.2.3)
                nidx = _STATIC_NAME.get(name, 0)
                out.extend(encode_integer(nidx, 4, 0x10))
                if not nidx:
                    out.extend(encode_string(name.encode(), self._huffman))
                out.extend(encode_string(value.encode("latin1"),
                                         self._huffman))
                continue
            full, nidx = self._find(name, value)
            if full:
                out.extend(encode_integer(full, 7, 0x80))
                continue
            out.extend(encode_integer(nidx, 6, 0x40))
            if not nidx:
                out.extend(encode_string(name.encode(), self._huffman))
            out.extend(encode_string(value.encode("latin1"), self._huffman))
            self._table.add(name, value)
        return bytes(out)
