"""Framework-native HTTP/1.1 client: keep-alive connections over the
Socket/fiber stack with buffered OR progressive response bodies.

The reference's Channel speaks HTTP as a first-class protocol
(policy/http_rpc_protocol.cpp client side) and supports reading big
responses progressively (progressive_reader.h: the app installs a
reader and body parts stream in as they arrive). This is that role,
idiomatically: ``HttpClient.request(...)`` returns (status, headers,
body); pass ``on_chunk=`` and body parts stream to the callback
instead, with the final return carrying empty body.

Response framing handled: Content-Length, chunked transfer-encoding
(each chunk delivered as parsed — this is what makes progressive
reading real), and close-delimited bodies (HTTP/1.0 style: EOF ends
the body). gzip/deflate Content-Encoding is decoded for buffered
bodies (progressive chunks are delivered raw).

HTTP/1.1 keep-alive is sequential per connection: responses complete
in request order, so pending calls form a FIFO on the socket — the
same pipelined-FIFO discipline the redis/memcache clients use. (Not
built on transport/pipelined.PipelinedClient because a response here
is a STREAM of events — head, N chunks, end — not the one-reply-per-
request contract its Batch machinery assumes; the two invariants that
matter are carried over instead: enqueue+write under one lock so FIFO
order matches wire order, and per-socket failure attribution so a
stale socket's death cannot fail calls in flight on its successor.)
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol)
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import create_client_socket

_MAX_HEADER = 64 * 1024
_MAX_CHUNK_LINE = 128


_FC = False          # unresolved sentinel (None is a valid answer)


def _fastcore():
    """The extension, or None — also None for a stale prebuilt .so that
    predates the http symbols (same memoized seam as protocol/http.py)."""
    global _FC
    if _FC is False:
        from brpc_tpu.native import fastcore
        m = fastcore.get()
        _FC = m if m is not None and hasattr(m, "http_parse_resp_head") \
            else None
    return _FC


class HttpClientError(ConnectionError):
    pass


class _RespState:
    """Per-socket response parse state (one response in flight at the
    head of the FIFO at any time — HTTP/1.1 keep-alive ordering)."""

    __slots__ = ("phase", "status", "headers", "mode", "remaining")

    def __init__(self):
        self.reset()

    def reset(self):
        self.phase = "head"     # head | body | chunk_size | chunk_data
        #                         | chunk_end | trailers
        self.status = 0
        self.headers: Dict[str, str] = {}
        self.mode = ""          # length | chunked | close
        self.remaining = 0


class HttpResponseProtocol(Protocol):
    """Parses HTTP/1.1 RESPONSES into events: ("head", status, headers),
    ("chunk", bytes), ("end", None). The server-side HttpProtocol parses
    requests; this is its client-side twin."""

    name = "http_client"
    min_probe_bytes = 7   # len("HTTP/1.")

    def parse(self, portal, socket):
        st = socket.user_data.get("http_resp_state")
        if st is None:
            st = _RespState()
            socket.user_data["http_resp_state"] = st
        if st.phase == "head":
            head = portal.peek_bytes(min(7, portal.size))
            if not b"HTTP/1.".startswith(head[:7]) and \
                    not head.startswith(b"HTTP/1."):
                return PARSE_TRY_OTHERS, None
            raw = portal.peek_bytes(min(portal.size, _MAX_HEADER))
            # fast lane: native head parse (httpparse.cc); DEFER (-2)
            # falls to the classic loop below so semantics are CPython's
            # on anything exotic (tests/test_http_native.py fuzzes both)
            parsed = None
            ext = _fastcore()
            if ext is not None:
                r = ext.http_parse_resp_head(raw, _MAX_HEADER)
                if r is None:
                    return PARSE_NOT_ENOUGH_DATA, None
                if isinstance(r, tuple):
                    parsed = r
                elif r == -1:
                    return PARSE_TRY_OTHERS, None
            if parsed is None:
                sep = raw.find(b"\r\n\r\n")
                if sep < 0:
                    if portal.size >= _MAX_HEADER:
                        return PARSE_TRY_OTHERS, None
                    return PARSE_NOT_ENOUGH_DATA, None
                lines = raw[:sep].split(b"\r\n")
                try:
                    _version, code, *_ = \
                        lines[0].decode("latin1").split(" ", 2)
                    status = int(code)
                except ValueError:
                    return PARSE_TRY_OTHERS, None
                headers = {}
                for line in lines[1:]:
                    k, _, v = line.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                parsed = (sep + 4, status, headers)
            header_len, st.status, st.headers = parsed
            portal.pop_front(header_len)
            # bodiless by RFC 9110 §6.4.1: HEAD responses (whatever
            # their entity headers claim), 1xx, 204, 304 — waiting for
            # the advertised body would stall until timeout
            expect = socket.user_data.get("http_expect_head")
            was_head = bool(expect.popleft()) if expect else False
            no_body = (was_head or st.status == 204 or st.status == 304
                       or 100 <= st.status < 200)
            te = st.headers.get("transfer-encoding", "").lower()
            if no_body:
                st.mode = "length"
                st.phase = "head"
            elif "chunked" in te:
                st.mode = "chunked"
                st.phase = "chunk_size"
            elif "content-length" in st.headers:
                st.mode = "length"
                try:
                    st.remaining = int(st.headers["content-length"])
                except ValueError:
                    return PARSE_TRY_OTHERS, None
                if st.remaining < 0:
                    return PARSE_TRY_OTHERS, None
                st.phase = "body" if st.remaining else "head"
            else:
                st.mode = "close"
                st.phase = "body"
            msg = ("head", st.status, dict(st.headers), st.mode)
            if st.phase == "head":       # empty length-delimited body
                st.reset()
                return PARSE_OK, [msg, ("end", None, None, None)]
            return PARSE_OK, [msg]

        if st.phase == "body" and st.mode == "length":
            if portal.size == 0:
                return PARSE_NOT_ENOUGH_DATA, None
            n = min(portal.size, st.remaining)
            data = portal.cut(n).to_bytes()
            st.remaining -= n
            if st.remaining == 0:
                st.reset()
                return PARSE_OK, [("chunk", data, None, None),
                                  ("end", None, None, None)]
            return PARSE_OK, [("chunk", data, None, None)]

        if st.phase == "body" and st.mode == "close":
            if portal.size == 0:
                return PARSE_NOT_ENOUGH_DATA, None
            data = portal.cut_all().to_bytes()
            # "end" arrives via socket EOF (socket failure completes the
            # close-delimited call)
            return PARSE_OK, [("chunk", data, None, None)]

        if st.phase == "chunk_size":
            raw = portal.peek_bytes(min(portal.size, _MAX_CHUNK_LINE))
            nl = raw.find(b"\r\n")
            if nl < 0:
                if portal.size >= _MAX_CHUNK_LINE:
                    return PARSE_TRY_OTHERS, None   # malformed: drop conn
                return PARSE_NOT_ENOUGH_DATA, None
            try:
                size = int(raw[:nl].split(b";")[0].strip() or b"0", 16)
            except ValueError:
                return PARSE_TRY_OTHERS, None
            portal.pop_front(nl + 2)
            if size == 0:
                st.phase = "trailers"
            else:
                st.remaining = size
                st.phase = "chunk_data"
            return PARSE_OK, []

        if st.phase == "chunk_data":
            # chunk payload + trailing CRLF
            if portal.size < st.remaining + 2:
                # stream partial chunk data as it arrives (progressive)
                if portal.size == 0:
                    return PARSE_NOT_ENOUGH_DATA, None
                n = min(portal.size, st.remaining)
                if n == 0:
                    return PARSE_NOT_ENOUGH_DATA, None
                data = portal.cut(n).to_bytes()
                st.remaining -= n
                return PARSE_OK, [("chunk", data, None, None)]
            data = portal.cut(st.remaining).to_bytes() if st.remaining \
                else b""
            portal.pop_front(2)
            st.remaining = 0
            st.phase = "chunk_size"
            return PARSE_OK, ([("chunk", data, None, None)] if data else [])

        if st.phase == "trailers":
            raw = portal.peek_bytes(min(portal.size, _MAX_HEADER))
            if raw.startswith(b"\r\n"):
                portal.pop_front(2)
                st.reset()
                return PARSE_OK, [("end", None, None, None)]
            sep = raw.find(b"\r\n\r\n")
            if sep < 0:
                if portal.size >= _MAX_HEADER:
                    return PARSE_TRY_OTHERS, None
                return PARSE_NOT_ENOUGH_DATA, None
            portal.pop_front(sep + 4)   # trailer headers discarded
            st.reset()
            return PARSE_OK, [("end", None, None, None)]

        return PARSE_TRY_OTHERS, None

    def process_inline(self, events, socket) -> bool:
        client = socket.user_data.get("http_client")
        if client is not None:
            for ev in events:
                client._on_event(socket, ev)
            # EOF semantics resolve AFTER the buffered tail parsed:
            # set_failed fires during the drain, before these bytes
            # reached the state machine (same input fiber: no races)
            if socket.failed and not socket.input_portal:
                client._resolve_eof(socket)
        return True

    def process(self, msg, socket):
        pass


class _Pending:
    __slots__ = ("done", "status", "headers", "body", "on_chunk", "mode",
                 "error", "sock")

    def __init__(self, on_chunk, sock):
        self.done = FiberEvent()
        self.status = 0
        self.headers: Dict[str, str] = {}
        self.body = bytearray()
        self.on_chunk = on_chunk
        self.mode = ""
        self.error: Optional[BaseException] = None
        self.sock = sock   # failure attribution: only THIS socket's
        #                    death may fail the call


class HttpClient:
    """Keep-alive HTTP/1.1 client over the framework stack.

    request() blocks the calling thread; requests on one client are
    serialized per connection (HTTP/1.1 ordering)."""

    def __init__(self, address: str | EndPoint, timeout_s: float = 10.0,
                 control: Optional[TaskControl] = None):
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address, default_scheme="tcp"))
        self._timeout_s = timeout_s
        self._control = control or global_control()
        self._messenger = InputMessenger(protocols=[HttpResponseProtocol()],
                                         control=self._control)
        self._lock = threading.Lock()
        self._socket = None
        self._pending: deque[_Pending] = deque()

    # ------------------------------------------------------------ plumbing
    def _get_socket(self):
        with self._lock:
            s = self._socket
            if s is not None and not s.failed:
                return s
        new = create_client_socket(
            self._endpoint, on_input=self._messenger.on_new_messages,
            control=self._control)
        new.user_data["http_client"] = self
        new.on_failed(self._on_socket_failed)
        with self._lock:
            if self._socket is not None and not self._socket.failed:
                winner, loser = self._socket, new
            else:
                self._socket, winner, loser = new, new, None
        if loser is not None:
            loser.set_failed(ConnectionError("duplicate connect"))
        return winner

    def _on_socket_failed(self, sock):
        # buffered tail bytes (drained before the EOF/RST was noticed)
        # still parse on the input fiber after this callback; final
        # judgment waits for them (process_inline -> _resolve_eof). With
        # nothing buffered, resolve now.
        if not (sock.input_portal and sock.input_portal.size):
            self._resolve_eof(sock)

    def _resolve_eof(self, sock) -> None:
        """One connection is dead and every byte it delivered has been
        parsed: a close-delimited body that got its head is COMPLETE;
        anything else in flight on THAT socket (no head yet, truncated
        length/chunked body) failed; queued calls behind it can never
        be answered. Calls on a different (successor) socket are
        untouched — the duplicate-connect loser or any stale socket
        failing late must not kill them."""
        state = sock.user_data.get("http_resp_state")
        with self._lock:
            mine = [p for p in self._pending if p.sock is sock]
            if not mine:
                return
            for p in mine:
                self._pending.remove(p)
        complete_close = (state is not None and state.mode == "close"
                          and state.phase == "body")
        for i, p in enumerate(mine):
            if i == 0 and complete_close and p.status:
                state.reset()
                p.done.set()
            else:
                p.error = p.error or (sock.fail_reason or
                                      ConnectionError("connection failed"))
                p.done.set()

    def _on_event(self, sock, ev) -> None:
        kind = ev[0]
        with self._lock:
            p = self._pending[0] if self._pending else None
        if p is None:
            return          # unsolicited data: ignore (conn will fail)
        if kind == "head":
            p.status, p.headers, p.mode = ev[1], ev[2], ev[3]
        elif kind == "chunk":
            if p.on_chunk is not None:
                try:
                    p.on_chunk(ev[1])
                except Exception:
                    pass
            else:
                p.body += ev[1]
        elif kind == "end":
            with self._lock:
                if self._pending and self._pending[0] is p:
                    self._pending.popleft()
            p.done.set()

    # ---------------------------------------------------------------- api
    def _issue(self, method, path, headers, body, on_chunk):
        try:
            sock = self._get_socket()
        except OSError as e:
            raise HttpClientError(f"connect failed: {e}") from e
        hdrs = {"host": f"{self._endpoint.host}:{self._endpoint.port}",
                "accept": "*/*"}
        if body:
            hdrs["content-length"] = str(len(body))
        if headers:
            hdrs.update({k.lower(): v for k, v in headers.items()})
        lines = [f"{method.upper()} {path} HTTP/1.1"]
        lines += [f"{k}: {v}" for k, v in hdrs.items()]
        wire = ("\r\n".join(lines) + "\r\n\r\n").encode("latin1") + body
        p = _Pending(on_chunk, sock)
        buf = IOBuf()
        buf.append(wire)
        with self._lock:
            # enqueue + write under ONE lock: pending order must match
            # wire order or FIFO response matching cross-wires
            # (pipelined.py documents the same invariant)
            self._pending.append(p)
            expect = sock.user_data.setdefault("http_expect_head",
                                               deque())
            expect.append(method.upper() == "HEAD")
            sock.write(buf)
        return p

    def _on_wait_timeout(self, p: "_Pending") -> None:
        with self._lock:
            try:
                self._pending.remove(p)
            except ValueError:
                pass
        # the connection is now desynced (a late response would be
        # matched to the wrong call): drop it
        p.sock.set_failed(TimeoutError("http response timed out"))

    def _finish(self, p: "_Pending", on_chunk):
        if p.error is not None:
            raise HttpClientError(str(p.error))
        body_out = bytes(p.body)
        if on_chunk is None:
            enc = p.headers.get("content-encoding", "").lower()
            try:
                if enc == "gzip":
                    import gzip
                    body_out = gzip.decompress(body_out)
                elif enc == "deflate":
                    import zlib
                    body_out = zlib.decompress(body_out)
            except Exception:
                pass   # deliver raw when decoding fails
        return p.status, p.headers, body_out

    def request(self, method: str, path: str,
                headers: Optional[Dict[str, str]] = None,
                body: bytes = b"",
                on_chunk: Optional[Callable[[bytes], None]] = None,
                timeout_s: Optional[float] = None,
                ) -> Tuple[int, Dict[str, str], bytes]:
        """Returns (status, headers, body); with on_chunk, body parts go
        to the callback (the progressive_reader.h role) and the returned
        body is empty. Raises HttpClientError on transport failure or
        timeout. BLOCKS the calling thread — from inside a fiber use
        request_async, or every scheduler worker can end up parked here
        while the fibers that would answer them can't run."""
        p = self._issue(method, path, headers, body, on_chunk)
        if not p.done.wait_pthread(timeout_s or self._timeout_s):
            self._on_wait_timeout(p)
            raise HttpClientError("http response timed out")
        return self._finish(p, on_chunk)

    async def request_async(self, method: str, path: str,
                            headers: Optional[Dict[str, str]] = None,
                            body: bytes = b"",
                            on_chunk: Optional[Callable[[bytes],
                                                        None]] = None,
                            timeout_s: Optional[float] = None,
                            ) -> Tuple[int, Dict[str, str], bytes]:
        """Fiber-friendly request(): awaits the completion instead of
        parking the worker thread."""
        p = self._issue(method, path, headers, body, on_chunk)
        if not await p.done.wait(timeout_s or self._timeout_s):
            self._on_wait_timeout(p)
            raise HttpClientError("http response timed out")
        return self._finish(p, on_chunk)

    def get(self, path: str, **kw):
        return self.request("GET", path, **kw)

    def post(self, path: str, body: bytes = b"",
             content_type: str = "application/octet-stream", **kw):
        headers = kw.pop("headers", {}) or {}
        headers.setdefault("content-type", content_type)
        return self.request("POST", path, headers=headers, body=body, **kw)

    def close(self) -> None:
        with self._lock:
            s, self._socket = self._socket, None
        if s is not None:
            s.set_failed(ConnectionError("client closed"))
