"""esp protocol (policy/esp_protocol.cpp, esp_message.h — the legacy
stargate messaging format). Re-designed compactly: a little-endian head
{to:u32 from:u32 flags:u32 msg_id:u32 body_len:u32} behind a 2-byte
magic "SG" so the parser can disambiguate, then the raw body. esp is
client-addressed (to/from stargate ids) with msg_id correlation, so
unlike nshead the client matches replies by msg_id, out-of-order safe."""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import create_client_socket

MAGIC = b"SG"
_HDR = struct.Struct("<2sIIIII")
HEADER_SIZE = 22
_MAX_BODY = 64 << 20


class EspMessage:
    __slots__ = ("to", "from_", "flags", "msg_id", "body")

    def __init__(self, body: bytes = b"", to: int = 0, from_: int = 0,
                 flags: int = 0, msg_id: int = 0):
        self.to = to
        self.from_ = from_
        self.flags = flags
        self.msg_id = msg_id
        self.body = bytes(body)

    def pack(self) -> bytes:
        return _HDR.pack(MAGIC, self.to, self.from_, self.flags,
                         self.msg_id, len(self.body)) + self.body


class EspProtocol(Protocol):
    name = "esp"

    def parse(self, portal, socket) -> Tuple[str, object]:
        head = portal.peek_bytes(min(HEADER_SIZE, portal.size))
        if MAGIC[:len(head)] != head[:2]:
            return PARSE_TRY_OTHERS, None
        if len(head) < HEADER_SIZE:
            return PARSE_NOT_ENOUGH_DATA, None
        _magic, to, from_, flags, msg_id, body_len = _HDR.unpack(head)
        if body_len > _MAX_BODY:
            socket.set_failed(ConnectionError("esp body exceeds max"))
            return PARSE_NOT_ENOUGH_DATA, None
        if portal.size < HEADER_SIZE + body_len:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(HEADER_SIZE)
        body = portal.cut(body_len).to_bytes()
        return PARSE_OK, EspMessage(body, to, from_, flags, msg_id)

    def process_inline(self, msg: EspMessage, socket) -> bool:
        client = socket.user_data.get("esp_client")
        if client is not None:
            client._on_reply(msg)
            return True
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        process_in_parse_order(socket, "esp", msg, self._run_handler)
        return True

    async def _run_handler(self, msg: EspMessage, socket):
        import inspect
        import time
        server = socket.user_data.get("server")
        handler = (getattr(server.options, "esp_service", None)
                   if server is not None else None)
        if handler is None:
            return       # esp has no error channel: drop, like the reference
        cost = server.on_request_start("esp.process")
        if not cost:
            return
        t0 = time.monotonic_ns()
        error = False
        reply = None
        try:
            r = handler(socket, msg)
            if inspect.isawaitable(r):
                r = await r
            reply = r
        except Exception:
            error = True
        server.on_request_end("esp.process",
                              (time.monotonic_ns() - t0) / 1e3, error, cost)
        if reply is None:
            return
        if isinstance(reply, (bytes, bytearray, memoryview)):
            reply = EspMessage(bytes(reply), to=msg.from_, from_=msg.to,
                               msg_id=msg.msg_id)
        out = IOBuf()
        out.append(reply.pack())
        socket.write(out)

    def process(self, msg, socket):
        raise AssertionError("esp messages are processed inline")


class EspClient:
    """msg_id-correlated client: safe for concurrent callers without FIFO
    ordering assumptions (esp servers may reply out of order)."""

    def __init__(self, address: str | EndPoint, stargate_id: int = 0,
                 timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        from brpc_tpu.butil.endpoint import str2endpoint
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address))
        self._stargate_id = stargate_id
        self._timeout_s = timeout_s
        self._control = control or global_control()
        self._messenger = InputMessenger(protocols=[ensure_registered()],
                                         control=self._control)
        self._lock = threading.Lock()
        self._socket = None
        self._next_id = 1
        self._pending: Dict[int, list] = {}   # msg_id -> [event, reply|err]

    def _get_socket(self):
        with self._lock:
            s = self._socket
        if s is not None and not s.failed:
            return s
        new = create_client_socket(
            self._endpoint, on_input=self._messenger.on_new_messages,
            control=self._control)
        new.user_data["esp_client"] = self
        new.on_failed(self._on_socket_failed)
        with self._lock:
            if self._socket is not None and not self._socket.failed:
                loser, new = new, self._socket
            else:
                self._socket, loser = new, None
        if loser is not None:
            loser.set_failed(ConnectionError("duplicate connect discarded"))
        return new

    def _on_socket_failed(self, socket):
        # Only fail calls that were written to THIS socket: a discarded
        # duplicate-connect loser must not flush calls in flight on the
        # winning connection (mirrors PipelinedClient._on_socket_failed).
        with self._lock:
            if self._socket is socket:
                self._socket = None
            failed = {i: s for i, s in self._pending.items()
                      if s[2] is socket}
            for i in failed:
                del self._pending[i]
        err = getattr(socket, "fail_reason", None) or \
            ConnectionError("esp connection failed")
        for slot in failed.values():
            slot[1] = err
            slot[0].set()

    def _on_reply(self, msg: EspMessage):
        with self._lock:
            slot = self._pending.pop(msg.msg_id, None)
        if slot is not None:
            slot[1] = msg
            slot[0].set()

    def _issue(self, to: int, body: bytes, flags: int):
        socket = self._get_socket()
        with self._lock:
            msg_id = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF or 1
            slot = [FiberEvent(), None, socket]
            self._pending[msg_id] = slot
        msg = EspMessage(body, to=to, from_=self._stargate_id, flags=flags,
                         msg_id=msg_id)
        out = IOBuf()
        out.append(msg.pack())
        if not socket.write(out):
            self._on_socket_failed(socket)
        return msg_id, slot

    def _settle(self, msg_id: int, slot, completed: bool) -> EspMessage:
        if not completed:
            with self._lock:
                self._pending.pop(msg_id, None)
            raise TimeoutError("esp call timed out")
        if isinstance(slot[1], BaseException):
            raise slot[1]
        return slot[1]

    def call(self, to: int, body: bytes, flags: int = 0) -> EspMessage:
        """BLOCKS the calling thread; fibers use call_async."""
        msg_id, slot = self._issue(to, body, flags)
        return self._settle(msg_id, slot,
                            slot[0].wait_pthread(self._timeout_s))

    async def call_async(self, to: int, body: bytes,
                         flags: int = 0) -> EspMessage:
        """Fiber-friendly call: awaits the reply instead of parking
        the worker thread."""
        msg_id, slot = self._issue(to, body, flags)
        return self._settle(msg_id, slot,
                            await slot[0].wait(self._timeout_s))

    def close(self):
        with self._lock:
            s, self._socket = self._socket, None
        if s is not None and not s.failed:
            s.set_failed(ConnectionError("esp client closed"))


_instance: Optional[EspProtocol] = None


def ensure_registered() -> EspProtocol:
    global _instance
    if _instance is None:
        _instance = EspProtocol()
        register_protocol(_instance)
    return _instance
