"""hulu_pbrpc + sofa_pbrpc framing variants
(policy/hulu_pbrpc_protocol.cpp, policy/sofa_pbrpc_protocol.cpp): the
baidu-family interop protocols. Both carry the same meta+payload model
as tpu_std behind different wire headers, exactly as the reference's
variants all funnel into the shared Controller/Server machinery.

hulu: "HULU" | body_size:u32be | meta_size:u32be | meta | payload
      (the 12-byte baidu_std-shaped header with hulu's magic)
sofa: "SOFA" | meta_size:u32be | body_size:u32be | reserved:u32be |
      meta | payload  (16-byte header)

The meta schema is our RpcMeta (the reference uses per-family metas;
re-designed here to one schema — cross-implementation interop with
legacy baidu services is out of scope, the capability is the framing +
dispatch plumbing selectable via ChannelOptions.protocol)."""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, register_protocol,
)
from brpc_tpu.protocol.tpu_std import RpcMessage, TpuStdProtocol, pack_message

_SOFA_HDR = struct.Struct(">4sIII")
_SOFA_HEADER_SIZE = 16


class HuluPbrpcProtocol(TpuStdProtocol):
    """Same 12-byte header layout as tpu_std, hulu magic — everything
    else (parse body, dispatch, response path) is inherited."""

    name = "hulu_pbrpc"
    MAGIC = b"HULU"


class SofaPbrpcProtocol(TpuStdProtocol):
    name = "sofa_pbrpc"
    MAGIC = b"SOFA"

    def frame(self, meta, payload, attachment=None, device_arrays=None,
              device_lane=False):
        # reuse tpu_std body building (device payload inlining included),
        # then swap the 12-byte header for sofa's 16-byte one — header
        # only, never flattening the body (zero-copy preserved)
        wire, lane = pack_message(meta, payload, attachment=attachment,
                                  device_arrays=device_arrays,
                                  device_lane=device_lane, magic=b"\x00\x00\x00\x00")
        _magic, body_size, meta_size = struct.unpack(
            ">4sII", wire.peek_bytes(12))
        wire.pop_front(12)
        out = IOBuf()
        out.append(_SOFA_HDR.pack(self.MAGIC, meta_size,
                                  body_size - meta_size, 0))
        out.append_buf(wire)
        return out, lane

    def parse(self, portal, socket) -> Tuple[str, object]:
        if portal.size < _SOFA_HEADER_SIZE:
            head = portal.peek_bytes(min(4, portal.size))
            if self.MAGIC[:len(head)] != head:
                return PARSE_TRY_OTHERS, None
            return PARSE_NOT_ENOUGH_DATA, None
        magic, meta_size, data_size, _reserved = _SOFA_HDR.unpack(
            portal.peek_bytes(_SOFA_HEADER_SIZE))
        if magic != self.MAGIC:
            return PARSE_TRY_OTHERS, None
        total = meta_size + data_size
        if portal.size < _SOFA_HEADER_SIZE + total:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(_SOFA_HEADER_SIZE)
        meta = pb.RpcMeta()
        meta.ParseFromString(portal.cut(meta_size).to_bytes())
        att_size = meta.attachment_size
        if att_size < 0 or att_size > data_size:
            # a lying attachment_size would eat the next frame's bytes and
            # desync the whole connection: fail it instead
            socket.set_failed(ConnectionError(
                f"sofa frame attachment_size {att_size} > data {data_size}"))
            return PARSE_NOT_ENOUGH_DATA, None
        payload = portal.cut(data_size - att_size)
        attachment = portal.cut(att_size)
        device_arrays = []
        device_recv = None
        if meta.device_payloads and any(not dp.inline_bytes
                                        for dp in meta.device_payloads):
            lane, device_recv = socket.take_device_payload_with_recv()
            if lane is not None:
                device_arrays = list(lane)
        msg = RpcMessage(meta, payload, attachment, device_arrays)
        msg.device_recv = device_recv
        return PARSE_OK, msg


_hulu: Optional[HuluPbrpcProtocol] = None
_sofa: Optional[SofaPbrpcProtocol] = None


def ensure_registered() -> Tuple[HuluPbrpcProtocol, SofaPbrpcProtocol]:
    global _hulu, _sofa
    if _hulu is None:
        _hulu = HuluPbrpcProtocol()
        register_protocol(_hulu)
    if _sofa is None:
        _sofa = SofaPbrpcProtocol()
        register_protocol(_sofa)
    return _hulu, _sofa
