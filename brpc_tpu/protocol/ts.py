"""MPEG-TS muxer (src/brpc/ts.{h,cpp} in the reference, 1477 LoC — the
HLS leg of the media stack: RTMP/FLV media remuxed into transport
stream segments).

Covers: 188-byte packets, PAT/PMT with MPEG-2 CRC32, PES packetization
with PTS (+PCR on the video PID), adaptation-field stuffing, continuity
counters. Stream types: H.264 video (0x1B), AAC audio (0x0F)."""

from __future__ import annotations

import struct
from typing import Iterator, List, NamedTuple, Optional

TS_PACKET_SIZE = 188
PAT_PID = 0x0000
PMT_PID = 0x1000
VIDEO_PID = 0x0100
AUDIO_PID = 0x0101
PROGRAM = 1
STREAM_TYPE_H264 = 0x1B
STREAM_TYPE_AAC = 0x0F
_SYNC = 0x47


def mpeg_crc32(data: bytes) -> int:
    """MPEG-2 CRC32: poly 0x04C11DB7, MSB-first, init 0xFFFFFFFF, no
    final xor, no reflection (different from crc32c)."""
    crc = 0xFFFFFFFF
    for byte in data:
        crc ^= byte << 24
        for _ in range(8):
            crc = ((crc << 1) ^ 0x04C11DB7) & 0xFFFFFFFF if crc & 0x80000000 \
                else (crc << 1) & 0xFFFFFFFF
    return crc


class TsError(Exception):
    pass


def _packet(pid: int, payload: bytes, counter: int, start: bool,
            adaptation: bytes = b"", pcr: Optional[int] = None):
    """One 188-byte packet; pads with an adaptation field as needed.
    Returns (packet_bytes, payload_bytes_consumed)."""
    if pcr is not None:
        base = pcr // 300
        ext = pcr % 300
        # 48-bit field: 33-bit base | 6 reserved (all-ones) | 9-bit ext =
        # bytes 2..7 of the 8-byte pack ([3:] would drop the top base byte
        # once the clock passes ~6 minutes)
        pcr_bytes = struct.pack(">Q", (base << 15) | (0x3F << 9) | ext)[2:]
        adaptation = bytes([0x10]) + pcr_bytes + adaptation  # PCR flag
    space = TS_PACKET_SIZE - 4
    af_len = len(adaptation)
    has_af = af_len > 0
    body_space = space - (1 + af_len if has_af else 0)
    if len(payload) < body_space:
        # stuff the adaptation field so payload fills to exactly 188
        pad = body_space - len(payload)
        if not has_af:
            if pad == 1:
                adaptation = b""
                has_af = True
                pad = 0
            else:
                adaptation = bytes([0x00]) + b"\xff" * (pad - 2)
                has_af = True
                pad = 0
        else:
            adaptation = adaptation + b"\xff" * pad
        af_len = len(adaptation)
        body_space = space - 1 - af_len
    take = payload[:body_space]
    header = bytes([
        _SYNC,
        (0x40 if start else 0) | (pid >> 8) & 0x1F,
        pid & 0xFF,
        (0x30 if has_af else 0x10) | (counter & 0x0F),
    ])
    out = header
    if has_af:
        out += bytes([af_len]) + adaptation
    out += take
    if len(out) != TS_PACKET_SIZE:
        raise TsError(f"internal: packet size {len(out)}")
    return out, len(take)


def _psi_section(table_id: int, body: bytes) -> bytes:
    # section_length covers body + crc
    sec = bytes([table_id]) + \
        struct.pack(">H", 0xB000 | (len(body) + 4 + 5)) + \
        struct.pack(">H", PROGRAM) + bytes([0xC1, 0x00, 0x00]) + body
    return sec + struct.pack(">I", mpeg_crc32(sec))


def pat_section() -> bytes:
    return _psi_section(0x00, struct.pack(">HH", PROGRAM,
                                          0xE000 | PMT_PID))


def pmt_section(has_video: bool = True, has_audio: bool = True) -> bytes:
    streams = b""
    if has_video:
        streams += bytes([STREAM_TYPE_H264]) + \
            struct.pack(">HH", 0xE000 | VIDEO_PID, 0xF000)
    if has_audio:
        streams += bytes([STREAM_TYPE_AAC]) + \
            struct.pack(">HH", 0xE000 | AUDIO_PID, 0xF000)
    # PCR must live on a PID that actually carries packets: audio-only
    # muxes clock off the audio PID
    pcr_pid = VIDEO_PID if has_video else AUDIO_PID
    body = struct.pack(">HH", 0xE000 | pcr_pid, 0xF000) + streams
    return _psi_section(0x02, body)


def pes_packet(stream_id: int, payload: bytes, pts_90k: Optional[int]) -> bytes:
    """PES with optional PTS (90kHz units)."""
    if pts_90k is None:
        header_data = b""
        flags = 0x00
    else:
        p = pts_90k & ((1 << 33) - 1)
        header_data = bytes([
            0x21 | ((p >> 29) & 0x0E),
            (p >> 22) & 0xFF,
            0x01 | ((p >> 14) & 0xFE),
            (p >> 7) & 0xFF,
            0x01 | ((p << 1) & 0xFE),
        ])
        flags = 0x80
    length = 3 + len(header_data) + len(payload)
    if length > 0xFFFF:
        length = 0      # unbounded (video PES commonly uses 0)
    return (b"\x00\x00\x01" + bytes([stream_id]) +
            struct.pack(">H", length) + bytes([0x80, flags,
                                               len(header_data)]) +
            header_data + payload)


class TsMuxer:
    """Feed ES frames, collect 188-byte packets. write_tables() first
    (and at segment boundaries for HLS)."""

    def __init__(self, has_video: bool = True, has_audio: bool = True):
        self._has_video = has_video
        self._has_audio = has_audio
        self._counters = {PAT_PID: 0, PMT_PID: 0, VIDEO_PID: 0,
                          AUDIO_PID: 0}
        self.packets: List[bytes] = []

    def _emit(self, pid: int, payload: bytes, pcr: Optional[int] = None):
        start = True
        while payload or start:
            pkt, consumed = _packet(pid, payload, self._counters[pid],
                                    start, pcr=pcr if start else None)
            self.packets.append(pkt)
            payload = payload[consumed:]
            self._counters[pid] = (self._counters[pid] + 1) & 0x0F
            start = False
            pcr = None

    def write_tables(self) -> None:
        # PSI sections are pointer_field-prefixed
        self._emit(PAT_PID, b"\x00" + pat_section())
        self._emit(PMT_PID, b"\x00" + pmt_section(self._has_video,
                                                  self._has_audio))

    def write_video(self, es: bytes, pts_90k: int) -> None:
        self._emit(VIDEO_PID, pes_packet(0xE0, es, pts_90k),
                   pcr=pts_90k * 300)

    def write_audio(self, es: bytes, pts_90k: int) -> None:
        self._emit(AUDIO_PID, pes_packet(0xC0, es, pts_90k))

    def flush(self) -> bytes:
        out, self.packets = b"".join(self.packets), []
        return out


# ------------------------------------------------------------- demux (test)

class TsPacket(NamedTuple):
    pid: int
    start: bool
    counter: int
    payload: bytes


def iter_packets(data: bytes) -> Iterator[TsPacket]:
    if len(data) % TS_PACKET_SIZE:
        raise TsError("stream not packet-aligned")
    for off in range(0, len(data), TS_PACKET_SIZE):
        pkt = data[off:off + TS_PACKET_SIZE]
        if pkt[0] != _SYNC:
            raise TsError(f"lost sync at {off}")
        pid = ((pkt[1] & 0x1F) << 8) | pkt[2]
        start = bool(pkt[1] & 0x40)
        counter = pkt[3] & 0x0F
        pos = 4
        if pkt[3] & 0x20:           # adaptation field
            pos += 1 + pkt[4]
        yield TsPacket(pid, start, counter, pkt[pos:])


def extract_pes(data: bytes, pid: int) -> List[bytes]:
    """Reassembled PES payloads (ES data after the PES header) for a pid."""
    out: List[bytes] = []
    cur: Optional[bytearray] = None
    for pkt in iter_packets(data):
        if pkt.pid != pid:
            continue
        if pkt.start:
            if cur is not None:
                out.append(bytes(cur))
            cur = bytearray(pkt.payload)
        elif cur is not None:
            cur += pkt.payload
    if cur is not None:
        out.append(bytes(cur))
    es_out = []
    for pes in out:
        if pes[:3] != b"\x00\x00\x01":
            raise TsError("bad PES start code")
        header_len = pes[8]
        es_out.append(bytes(pes[9 + header_len:]))
    return es_out
