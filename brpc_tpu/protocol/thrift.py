"""Thrift framed protocol: TBinaryProtocol codec + framed transport,
client and server (policy/thrift_protocol.cpp, thrift_message.* in the
reference — 763 LoC of framed TBinary handling wired into the Protocol
table; brpc serves thrift via ThriftService::ProcessThriftFramedRequest).

No thrift codegen is required (the reference needs generated classes;
here the wire model is dynamic): a struct is ``{field_id: TVal(ttype,
value)}``, lists/sets are ``TList(elem_ttype, [values])``, maps are
``TMap(ktype, vtype, {k: v})``. Methods take/return such structs.

Framing: u32 big-endian length, then TBinary strict message:
  i32 (0x8001_0000 | msg_type) | string method | i32 seqid | args struct
"""

from __future__ import annotations

import struct
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.pipelined import PipelinedClient

VERSION_1 = 0x80010000
_VERSION_MASK = 0xFFFF0000

# message types
MSG_CALL = 1
MSG_REPLY = 2
MSG_EXCEPTION = 3
MSG_ONEWAY = 4

# TType wire ids
T_STOP = 0
T_VOID = 1
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15

_MAX_FRAME = 64 << 20
_MAX_DEPTH = 32
_MAX_CONTAINER = 1 << 24


class TVal(NamedTuple):
    ttype: int
    value: Any


class TList(NamedTuple):
    elem_ttype: int
    values: List[Any]


class TMap(NamedTuple):
    key_ttype: int
    val_ttype: int
    items: Dict[Any, Any]


class ThriftError(Exception):
    """TApplicationException from the peer (type, message)."""

    def __init__(self, message: str, type_: int = 6):
        super().__init__(message)
        self.type = type_


class _BadWire(Exception):
    pass


# ------------------------------------------------------------------ codec

class TBinaryWriter:
    def __init__(self):
        self._parts: List[bytes] = []

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def write_message_begin(self, method: str, msg_type: int, seqid: int):
        self._parts.append(struct.pack(">I", VERSION_1 | msg_type))
        self.write_string(method)
        self._parts.append(struct.pack(">i", seqid))

    def write_string(self, s):
        if isinstance(s, str):
            s = s.encode()
        self._parts.append(struct.pack(">i", len(s)))
        self._parts.append(bytes(s))

    def write_value(self, ttype: int, value):
        p = self._parts
        if ttype == T_BOOL:
            p.append(b"\x01" if value else b"\x00")
        elif ttype == T_BYTE:
            p.append(struct.pack(">b", value))
        elif ttype == T_I16:
            p.append(struct.pack(">h", value))
        elif ttype == T_I32:
            p.append(struct.pack(">i", value))
        elif ttype == T_I64:
            p.append(struct.pack(">q", value))
        elif ttype == T_DOUBLE:
            p.append(struct.pack(">d", value))
        elif ttype == T_STRING:
            self.write_string(value)
        elif ttype == T_STRUCT:
            self.write_struct(value)
        elif ttype in (T_LIST, T_SET):
            lst: TList = value
            p.append(struct.pack(">bi", lst.elem_ttype, len(lst.values)))
            for v in lst.values:
                self.write_value(lst.elem_ttype, v)
        elif ttype == T_MAP:
            m: TMap = value
            p.append(struct.pack(">bbi", m.key_ttype, m.val_ttype,
                                 len(m.items)))
            for k, v in m.items.items():
                self.write_value(m.key_ttype, k)
                self.write_value(m.val_ttype, v)
        else:
            raise TypeError(f"cannot write ttype {ttype}")

    def write_struct(self, fields: Dict[int, TVal]):
        for fid, tv in fields.items():
            self._parts.append(struct.pack(">bh", tv.ttype, fid))
            self.write_value(tv.ttype, tv.value)
        self._parts.append(b"\x00")     # T_STOP


class TBinaryReader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise _BadWire("truncated thrift payload")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_message_begin(self) -> Tuple[str, int, int]:
        word = struct.unpack(">I", self._take(4))[0]
        if word & _VERSION_MASK != VERSION_1:
            raise _BadWire(f"bad thrift version word 0x{word:08x}")
        msg_type = word & 0xFF
        method = self.read_string().decode("utf-8", "replace")
        seqid = struct.unpack(">i", self._take(4))[0]
        return method, msg_type, seqid

    def read_string(self) -> bytes:
        n = struct.unpack(">i", self._take(4))[0]
        if n < 0 or n > _MAX_FRAME:
            raise _BadWire("bad string length")
        return self._take(n)

    def read_value(self, ttype: int, depth: int = 0):
        if depth > _MAX_DEPTH:
            raise _BadWire("thrift nesting too deep")
        if ttype == T_BOOL:
            return self._take(1) != b"\x00"
        if ttype == T_BYTE:
            return struct.unpack(">b", self._take(1))[0]
        if ttype == T_I16:
            return struct.unpack(">h", self._take(2))[0]
        if ttype == T_I32:
            return struct.unpack(">i", self._take(4))[0]
        if ttype == T_I64:
            return struct.unpack(">q", self._take(8))[0]
        if ttype == T_DOUBLE:
            return struct.unpack(">d", self._take(8))[0]
        if ttype == T_STRING:
            return self.read_string()
        if ttype == T_STRUCT:
            return self.read_struct(depth + 1)
        if ttype in (T_LIST, T_SET):
            elem, n = struct.unpack(">bi", self._take(5))
            if n < 0 or n > _MAX_CONTAINER:
                raise _BadWire("bad container length")
            return TList(elem, [self.read_value(elem, depth + 1)
                                for _ in range(n)])
        if ttype == T_MAP:
            kt, vt, n = struct.unpack(">bbi", self._take(6))
            if n < 0 or n > _MAX_CONTAINER:
                raise _BadWire("bad map length")
            items = {}
            for _ in range(n):
                k = self.read_value(kt, depth + 1)
                if isinstance(k, (bytearray, TList, TMap, dict)):
                    k = bytes(k) if isinstance(k, bytearray) else repr(k)
                items[k] = self.read_value(vt, depth + 1)
            return TMap(kt, vt, items)
        raise _BadWire(f"unknown ttype {ttype}")

    def read_struct(self, depth: int = 0) -> Dict[int, TVal]:
        if depth > _MAX_DEPTH:
            raise _BadWire("thrift nesting too deep")
        fields: Dict[int, TVal] = {}
        while True:
            ttype = struct.unpack(">b", self._take(1))[0]
            if ttype == T_STOP:
                return fields
            fid = struct.unpack(">h", self._take(2))[0]
            fields[fid] = TVal(ttype, self.read_value(ttype, depth + 1))


def pack_message(method: str, msg_type: int, seqid: int,
                 fields: Dict[int, TVal]) -> bytes:
    w = TBinaryWriter()
    w.write_message_begin(method, msg_type, seqid)
    w.write_struct(fields)
    payload = w.bytes()
    return struct.pack(">I", len(payload)) + payload


class ThriftMessage(NamedTuple):
    method: str
    msg_type: int
    seqid: int
    fields: Dict[int, TVal]


def unpack_message(payload: bytes) -> ThriftMessage:
    r = TBinaryReader(payload)
    method, msg_type, seqid = r.read_message_begin()
    fields = r.read_struct()
    return ThriftMessage(method, msg_type, seqid, fields)


def app_exception_fields(message: str, type_: int = 6) -> Dict[int, TVal]:
    return {1: TVal(T_STRING, message), 2: TVal(T_I32, type_)}


# ----------------------------------------------------------------- server

class ThriftService:
    """Method table for native thrift handlers (ThriftService in
    brpc/thrift_service.h). Handlers take (socket, args_fields) and
    return result fields ``{0: TVal(...)}`` (0 = success field), a bare
    TVal (wrapped as field 0), or None (void)."""

    def __init__(self):
        self._methods: Dict[str, Callable] = {}

    def add_method(self, name: str, fn: Callable) -> None:
        self._methods[name] = fn

    def method(self, name: Optional[str] = None):
        def deco(fn):
            self.add_method(name or fn.__name__, fn)
            return fn
        return deco

    def find(self, name: str) -> Optional[Callable]:
        return self._methods.get(name)


class ThriftProtocol(Protocol):
    name = "thrift"

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        head = portal.peek_bytes(min(8, portal.size))
        if len(head) < 8:
            # need length + version word to claim the bytes
            if len(head) >= 6 and head[4:6] != b"\x80\x01":
                return PARSE_TRY_OTHERS, None
            return PARSE_NOT_ENOUGH_DATA, None
        if head[4:6] != b"\x80\x01":
            return PARSE_TRY_OTHERS, None
        length = struct.unpack(">I", head[:4])[0]
        if length > _MAX_FRAME:
            socket.set_failed(ConnectionError(
                f"thrift frame of {length} bytes exceeds max"))
            return PARSE_NOT_ENOUGH_DATA, None
        if portal.size < 4 + length:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(4)
        payload = portal.cut(length).to_bytes()
        try:
            msg = unpack_message(payload)
        except _BadWire as e:
            socket.set_failed(ConnectionError(f"corrupt thrift frame: {e}"))
            return PARSE_NOT_ENOUGH_DATA, None
        return PARSE_OK, msg

    # -------------------------------------------------------------- process
    def process_inline(self, msg: ThriftMessage, socket) -> bool:
        client = socket.user_data.get("thrift_client")
        if client is not None:
            client._on_reply(socket, msg)
            return True
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        process_in_parse_order(socket, "thrift", msg, self._run_method)
        return True

    async def _run_method(self, msg: ThriftMessage, socket):
        import inspect
        import time
        server = socket.user_data.get("server")
        service: Optional[ThriftService] = (
            getattr(server.options, "thrift_service", None)
            if server is not None else None)
        oneway = msg.msg_type == MSG_ONEWAY

        def reply(msg_type: int, fields: Dict[int, TVal]):
            if oneway:
                return
            buf = IOBuf()
            buf.append(pack_message(msg.method, msg_type, msg.seqid, fields))
            socket.write(buf)

        if service is None:
            reply(MSG_EXCEPTION, app_exception_fields(
                "this server has no thrift_service installed", 5))
            return
        handler = service.find(msg.method)
        if handler is None:
            reply(MSG_EXCEPTION, app_exception_fields(
                f"unknown method {msg.method!r}", 1))   # UNKNOWN_METHOD
            return
        cost = server.on_request_start(f"thrift.{msg.method}")
        if not cost:
            reply(MSG_EXCEPTION, app_exception_fields(
                "max_concurrency reached", 5))           # INTERNAL_ERROR
            return
        t0 = time.monotonic_ns()
        error = False
        try:
            r = handler(socket, msg.fields)
            if inspect.isawaitable(r):
                r = await r
            if r is None:
                fields: Dict[int, TVal] = {}
            elif isinstance(r, TVal):
                fields = {0: r}
            else:
                fields = r
            reply(MSG_REPLY, fields)
        except ThriftError as e:
            error = True
            reply(MSG_EXCEPTION, app_exception_fields(str(e), e.type))
        except Exception as e:
            error = True
            reply(MSG_EXCEPTION, app_exception_fields(
                f"handler error: {e}", 6))               # INTERNAL_ERROR
        server.on_request_end(f"thrift.{msg.method}",
                              (time.monotonic_ns() - t0) / 1e3, error, cost)

    def process(self, msg, socket):
        raise AssertionError("thrift messages are processed inline")


# ----------------------------------------------------------------- client

class ThriftClient(PipelinedClient):
    """Framed TBinary client: ``call(method, fields)`` returns the reply's
    result fields (raising ThriftError for exception replies);
    ``call_oneway`` fires and forgets."""

    user_data_key = "thrift_client"

    def __init__(self, address: str | EndPoint, timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        super().__init__(address, ensure_registered(), timeout_s=timeout_s,
                         control=control)
        self._seq_lock = threading.Lock()
        self._seq = 0

    def _next_seqid(self) -> int:
        with self._seq_lock:
            self._seq = (self._seq + 1) & 0x7FFFFFFF
            return self._seq

    def _finish_call(self, reply: ThriftMessage, method: str, seqid: int,
                     batch) -> Dict[int, TVal]:
        if reply.seqid != seqid or reply.method != method:
            if batch.socket is not None:
                batch.socket.set_failed(
                    ConnectionError("thrift reply desync"))
            raise ThriftError("reply desync (seqid/method mismatch)", 4)
        if reply.msg_type == MSG_EXCEPTION:
            msg_f = reply.fields.get(1)
            type_f = reply.fields.get(2)
            raise ThriftError(
                msg_f.value.decode("utf-8", "replace") if msg_f else
                "application exception",
                type_f.value if type_f else 6)
        return reply.fields

    def call(self, method: str, fields: Optional[Dict[int, TVal]] = None
             ) -> Dict[int, TVal]:
        seqid = self._next_seqid()
        wire = pack_message(method, MSG_CALL, seqid, fields or {})
        batch = self._start(wire, 1)
        reply = self._wait(batch, f"thrift {method!r}")[0]
        return self._finish_call(reply, method, seqid, batch)

    async def call_async(self, method: str,
                         fields: Optional[Dict[int, TVal]] = None
                         ) -> Dict[int, TVal]:
        seqid = self._next_seqid()
        wire = pack_message(method, MSG_CALL, seqid, fields or {})
        batch = self._start(wire, 1)
        reply = (await self._wait_async(batch, f"thrift {method!r}"))[0]
        return self._finish_call(reply, method, seqid, batch)

    def call_oneway(self, method: str,
                    fields: Optional[Dict[int, TVal]] = None) -> None:
        wire = pack_message(method, MSG_ONEWAY, self._next_seqid(),
                            fields or {})
        socket = self._get_socket()
        buf = IOBuf()
        buf.append(wire)
        socket.write(buf)


_instance: Optional[ThriftProtocol] = None


def ensure_registered() -> ThriftProtocol:
    global _instance
    if _instance is None:
        _instance = ThriftProtocol()
        register_protocol(_instance)
    return _instance
