"""ici:// — the real device-fabric data plane (the RDMA slot).

Where the reference grafts ibverbs onto Socket — TCP handshake exchanging
GID/QPN then RC queue-pair bring-up (rdma/rdma_endpoint.h:64 state
machine, :95-109), zero-copy sends from registered blocks
(CutFromIOBufList :82), sliding-window flow control with piggybacked
ACKs (:138,:235-241), and a registered-memory block pool
(rdma/block_pool.cpp:52) — this transport grafts the PjRt fabric:

* **Bootstrap/control stream**: TCP (the reference's handshake +
  FALLBACK_TCP lane). Carries 13-byte-framed control/app frames.
* **Hello handshake** (the GID/QPN exchange): each side sends its
  process uuid, PjRt transfer-server address, advertised recv window,
  and recv-device ordinal before anything else.
* **Device lane**: sender registers the batch with its process-global
  PjRt transfer server (``jax.experimental.transfer``) and sends a
  small descriptor frame; the RECEIVER pulls the arrays directly onto
  its own device via PjRt DMA — receiver-driven placement, the moral
  twin of RDMA's pre-posted recv buffers. No numpy round-trip is on the
  data path. Same-process peers short-circuit through an in-process
  registry + ``jax.device_put`` (a device-to-device copy, ICI on real
  multi-chip hardware).
* **Flow control**: at most ``peer_window`` un-ACKed device batches in
  flight per connection; every frame header piggybacks the cumulative
  consumed count, and a bare ACK frame is pushed once half the window
  is unacknowledged with no reverse traffic (RdmaEndpoint::SendAck +
  imm-carried ack counts). A window-stalled sender parks exactly like a
  TCP-blocked one: BlockingIOError -> KeepWrite fiber waits for the
  writable event that ACK arrival fires.
* **Recv budget**: inbound batches reserve size-classed bytes from a
  DeviceRecvPool (butil/device_pool.py — block_pool.cpp's size classes
  as HBM admission control) before the pull is issued; the reservation
  releases when the app drops the arrays.

Frame format (all big-endian):
    type:u8  ack:u64  len:u32  payload[len]
    type 0 app bytes
    type 1 pull descriptor: uuid:u64, count:u16, then per array
           {dtype_len:u8, dtype, rank:u8, dims:i64*rank, nbytes:u64}
    type 2 hello (json)
    type 3 bare ack (payload empty, or a u32 adaptive window grant —
           header ack is the message, the grant is the receiver
           resizing the sender's pipeline from its admission headroom)
    type 4 staged batch (numpy fallback when either side lacks a
           transfer server — the old tpud lane, clearly second-class)
    type 5 coalesced group: mode:u8 (0 descriptor / 1 staged),
           count:u16, then mode 0: uid:u64 + per sub-batch
           {count:u16, array specs as type 1}; mode 1: per sub-batch
           {len:u32, staged blob}. One registration / one receiver
           reservation for N small batches; window + ack accounting
           stays per sub-batch.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import struct
import threading
import uuid as uuidlib
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from brpc_tpu.butil.device_pool import (BLOCK_CLASSES, DeviceRecvPool,
                                        round_to_class)

logger = logging.getLogger("brpc_tpu.ici")
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.transport import device_stats as _dev_stats
from brpc_tpu.transport.base import Conn, Listener, Transport
from brpc_tpu.transport.tcp import TcpConn, TcpTransport
from brpc_tpu.transport.tpud import (_decode_device_batch,
                                     _encode_device_batch, _np_dtype)

F_BYTES = 0
F_DESCRIPTOR = 1
F_HELLO = 2
F_ACK = 3
F_STAGED = 4
F_COALESCED = 5
_HDR = struct.Struct(">BQI")
_MAX_FRAME = 256 << 20
_MAX_OUT = 64 << 20
DEFAULT_WINDOW = 32
# cap on framed-but-unwritten bytes per flush pass: one gather pass
# frames every sendable queue item up to this, then pays ONE TCP write
_FLUSH_CHUNK = 1 << 20

_jax_mod = None


def _jax():
    """Module-cached jax import: the take path runs per batch and the
    `import jax` statement is a sys.modules dict hit + attr dance we
    don't need to repeat there."""
    global _jax_mod
    if _jax_mod is None:
        import jax
        _jax_mod = jax
    return _jax_mod


def _stager():
    """The process-wide pinned H2D stager (plain device_put when the
    native pinned arena or jax transfer runtime is absent)."""
    from brpc_tpu.butil.device_pool import global_pinned_stager
    return global_pinned_stager()

_PROC_UUID = uuidlib.uuid4().hex

# sender-side registry for same-process peers: uuid -> arrays
_local_exchange: Dict[int, list] = {}
_local_lock = threading.Lock()

_uuid_base = int.from_bytes(os.urandom(4), "big")
_uuid_counter = itertools.count(1)


def _next_uuid() -> int:
    return (_uuid_base << 32) | (next(_uuid_counter) & 0xFFFFFFFF)


# ------------------------------------------------------------------ PjRt
_server_lock = threading.Lock()
_transfer_server = None
_transfer_failed = False
_transfer_error: Optional[str] = None
_conn_cache: Dict[str, object] = {}
_lane_status_var = None


def _postfork_reset() -> None:
    """Fork hygiene: the PjRt transfer server and its connection cache
    are device-runtime handles owned by the parent — a forked shard
    must re-probe the lane itself (or, the normal case, never touch
    the device at all)."""
    global _transfer_server, _transfer_failed, _transfer_error
    global _conn_cache, _lane_status_var, _server_lock
    _transfer_server = None
    _transfer_failed = False
    _transfer_error = None
    _conn_cache = {}
    _lane_status_var = None
    _server_lock = threading.Lock()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the lane state it resets)

_postfork.register("transport.ici", _postfork_reset)


def _publish_lane_status() -> None:
    """Expose transfer-server state as a bvar (/vars ici_transfer_lane)
    so lane degradation is observable, not a silent latch."""
    global _lane_status_var
    try:
        from brpc_tpu.bvar import Status
        if _lane_status_var is None:
            _lane_status_var = Status("init").expose("ici_transfer_lane")
        _lane_status_var.set_value(
            "up" if _transfer_server is not None
            else f"down: {_transfer_error or 'not started'}")
    except Exception:
        pass


def transfer_lane_status() -> str:
    """'up' | 'down: <reason>' | 'not started' — the startup-probe hook
    (rdma_helper.cpp's global-init + fallback story made queryable)."""
    if _transfer_server is not None:
        return "up"
    if _transfer_failed:
        return f"down: {_transfer_error}"
    return "not started"


def _get_transfer_server():
    """Process-global PjRt transfer server (the rdma_helper.cpp global
    init slot). None when jax/the backend doesn't support it — the
    staged lane takes over (loudly: warning log + bvar, and
    BRPC_TPU_ICI_REQUIRE_PULL=1 turns degradation into an error)."""
    global _transfer_server, _transfer_failed, _transfer_error
    if os.environ.get("BRPC_TPU_ICI_FORCE_STAGED"):
        return None       # test/ops knob: exercise the degraded lane
    if _transfer_server is not None or _transfer_failed:
        return _transfer_server
    with _server_lock:
        if _transfer_server is not None or _transfer_failed:
            return _transfer_server
        try:
            import jax
            from jax.experimental import transfer

            from brpc_tpu.butil.jax_env import apply_jax_platforms_env
            apply_jax_platforms_env()   # env choice beats plugin override
            client = jax.devices()[0].client
            # explicit socket transport addresses: the default local bulk
            # transport only moves bytes within one process (aborts on a
            # cross-process pull); binding sockets gives the DCN lane
            host = os.environ.get("BRPC_TPU_TRANSFER_HOST", "0.0.0.0")
            _transfer_server = transfer.start_transfer_server(
                client, f"{host}:0", [f"{host}:0"])
            logger.info("ici: PjRt transfer server up at %s",
                        _transfer_server.address())
        except Exception as e:
            _transfer_failed = True
            _transfer_server = None
            _transfer_error = f"{type(e).__name__}: {e}"
            if os.environ.get("BRPC_TPU_ICI_REQUIRE_PULL"):
                raise ConnectionError(
                    f"ici: PjRt transfer server unavailable and "
                    f"BRPC_TPU_ICI_REQUIRE_PULL is set: {_transfer_error}")
            logger.warning(
                "ici: PjRt transfer server unavailable — device payloads "
                "DEGRADE to the host-staged lane (%s)", _transfer_error)
        _publish_lane_status()
    return _transfer_server


def _get_pull_conn(address: str):
    """Cached TransferConnection to a peer's transfer server."""
    srv = _get_transfer_server()
    if srv is None:
        raise ConnectionError("no local transfer server to pull with")
    conn = _conn_cache.get(address)
    if conn is None:
        with _server_lock:
            conn = _conn_cache.get(address)
            if conn is None:
                conn = srv.connect(address)
                _conn_cache[address] = conn
    return conn


def _canonical_addr(addr: str, peer_host: str) -> str:
    """The transfer server binds [::]:port; rewrite the wildcard host to
    the address we already reach the peer at (the TCP bootstrap host)."""
    host, _, port = addr.rpartition(":")
    if host in ("[::]", "0.0.0.0", ""):
        return f"{peer_host}:{port}"
    return addr


# shared default pool: one budget per process, like the reference's one
# block pool per NIC (rdma/block_pool.cpp global region registry)
_default_pool = DeviceRecvPool()


_lazy_adders: List["_LazyAdder"] = []


class _LazyAdder:
    """Counter that only materializes its bvar on first use. Instances
    register themselves so ``expose_ici_vars`` (called at Server.start)
    can RE-expose a materialized counter a test fixture's
    unexpose_all() stripped — without the re-expose, a server restart
    silently dropped every ici_* counter from /vars."""

    def __init__(self, name: str):
        self._name = name
        self._var = None
        _lazy_adders.append(self)

    def add(self, n: int) -> None:
        try:
            if self._var is None:
                from brpc_tpu.bvar import Adder
                self._var = Adder().expose(self._name)
            self._var.add(n)
        except Exception:
            pass

    def get_value(self) -> int:
        var = self._var
        try:
            return int(var.get_value()) if var is not None else 0
        except Exception:
            return 0

    def reexpose_counter(self) -> None:
        try:
            if self._var is not None:
                self._var.expose(self._name)
        except Exception:
            pass


# await_pull registrations whose peer died before pulling: the transfer
# API has no cancel, so these stay pinned until process exit — counted
# here so the leak is observable (/vars ici_unpulled_registrations).
# UPPER BOUND: un-ACKed pull-registered batches at close; a batch the
# peer pulled but had not yet acknowledged is counted too.
_unpulled_registrations = _LazyAdder("ici_unpulled_registrations")

# the HBM those leaked registrations pin, and the circuit breaker that
# BOUNDS it — attributed PER PEER EPOCH so one flapping peer degrades
# only itself (block_pool.cpp:271-340 freelist hygiene, adapted to an
# API with no cancel). The epoch is the peer's per-process uuid from
# the hello: a restarted peer arrives under a fresh epoch with a zero
# count, so the breaker recovers on reconnect. The GLOBAL cap stays —
# the leaked registrations of dead epochs remain pinned (the transfer
# API has no cancel), so the process-wide bound cannot honestly decay;
# past it every peer degrades to the host-staged lane.
# /vars ici_unpulled_bytes tracks the global estimate.
_unpulled_bytes = _LazyAdder("ici_unpulled_bytes")
# the real leaked/reclaimed counter PAIR the /device page surfaces:
# leaked = bytes a closing conn abandoned (un-ACKed pull registrations
# plus same-process exchange entries handed to the grace queue),
# reclaimed = bytes the grace sweep actually dropped. leaked - reclaimed
# is the live pinned estimate an operator watches.
_leaked_bytes_counter = _LazyAdder("ici_leaked_bytes")
_reclaimed_bytes_counter = _LazyAdder("ici_reclaimed_bytes")
_leaked_pull_bytes = [0]                    # global, all epochs
_leaked_by_epoch: Dict[str, int] = {}       # peer proc uuid -> bytes
_LEAK_CAP_BYTES = int(os.environ.get(
    "BRPC_TPU_ICI_PULL_LEAK_CAP", 256 << 20))          # per peer epoch
# process-wide hard bound. When an operator set PULL_LEAK_CAP as a
# strict HBM bound (its pre-per-epoch meaning) and no global cap, that
# value stays the global bound too — per-epoch attribution must not
# silently multiply a configured footprint limit.
_LEAK_GLOBAL_CAP_BYTES = int(
    os.environ.get("BRPC_TPU_ICI_PULL_LEAK_GLOBAL_CAP")
    or os.environ.get("BRPC_TPU_ICI_PULL_LEAK_CAP")
    or (1 << 30))
_epoch_trips_logged: set = set()


_leak_breaker_logged = [False]


def _note_leaked(peer_epoch: Optional[str], nbytes: int) -> None:
    """Attribute un-pulled registration bytes to the peer epoch that
    abandoned them (called under _local_lock by close paths)."""
    _leaked_pull_bytes[0] += nbytes
    if peer_epoch:
        _leaked_by_epoch[peer_epoch] = \
            _leaked_by_epoch.get(peer_epoch, 0) + nbytes
        if len(_leaked_by_epoch) > 4096:    # bound dead-epoch bookkeeping
            # keep the heaviest offenders; the global counter still
            # carries every byte
            for k in sorted(_leaked_by_epoch,
                            key=_leaked_by_epoch.get)[:2048]:
                del _leaked_by_epoch[k]


def _pull_lane_allowed(peer_epoch: Optional[str] = None) -> bool:
    if _leaked_pull_bytes[0] >= _LEAK_GLOBAL_CAP_BYTES:
        if not _leak_breaker_logged[0]:
            # once, on the open->tripped transition (runs per batch)
            _leak_breaker_logged[0] = True
            logger.warning(
                "ici: leaked pull registrations estimated at ~%d MB "
                "process-wide (global cap %d MB, an UPPER BOUND — "
                "pulled-but-unacked batches count too) — ALL lane "
                "batches use the host-staged path. Raise "
                "BRPC_TPU_ICI_PULL_LEAK_GLOBAL_CAP to re-enable.",
                _leaked_pull_bytes[0] >> 20, _LEAK_GLOBAL_CAP_BYTES >> 20)
        return False
    if peer_epoch and \
            _leaked_by_epoch.get(peer_epoch, 0) >= _LEAK_CAP_BYTES:
        if peer_epoch not in _epoch_trips_logged:
            _epoch_trips_logged.add(peer_epoch)
            logger.warning(
                "ici: peer epoch %s abandoned ~%d MB of pull "
                "registrations (per-epoch cap %d MB) — its lane "
                "batches degrade to the host-staged path until it "
                "reconnects under a fresh epoch",
                peer_epoch[:16], _leaked_by_epoch[peer_epoch] >> 20,
                _LEAK_CAP_BYTES >> 20)
        return False    # this epoch's own abandonment record gates it
    return True


# same-process exchange entries from closed connections are reclaimed on
# a grace timer, not immediately: close() flushes queued descriptor
# frames, so the peer may legitimately still take them — an instant pop
# would turn that take into an error. Tunable so soak tests can cycle
# quickly (flag ici_reclaim_grace_s).
from brpc_tpu.butil.flags import define_flag as _define_flag, flag as _flag

_define_flag("ici_reclaim_grace_s", 30.0,
             "seconds a closed connection's same-process exchange "
             "entries linger before reclaim (peer may still take them)")

# --- device-lane speed-run knobs (docs/performance.md "Device lane
# tuning"): the idle-ACK timer closes the "cells only balance after
# close" gap, coalescing collapses bursts of tiny batches into one
# frame/registration/reservation, and the adaptive grant lets a
# receiver with headroom deepen the sender's pipeline.
_define_flag("ici_idle_ack_ms", 2.0,
             "idle-ACK timer: a conn that consumed batches but has no "
             "reverse traffic sends a bare ACK after this many ms so "
             "the sender's window reopens (and its /device cells "
             "balance) without waiting for close; <=0 disables")
_define_flag("ici_coalesce_bytes", 16 << 10,
             "lane batches whose arrays total at most this many bytes "
             "are eligible to coalesce into one descriptor frame / one "
             "pull registration / one receiver reservation; <=0 "
             "disables coalescing")
_define_flag("ici_coalesce_max", 16,
             "max lane batches per coalesced frame (the flush-on-"
             "window-or-bytes cap)")
_define_flag("ici_adaptive_window", True,
             "receivers ride a window grant on bare ACKs sized from "
             "pool headroom: free pool -> grant 2x the hello window "
             "(deeper pipelining), pool under pressure -> window/4")


def _reclaim_grace_s() -> float:
    return float(_flag("ici_reclaim_grace_s"))


_reclaim_queue: Deque[Tuple[float, int]] = deque()
# uids on the grace queue, with the byte footprint their close charged
# to ici_leaked_bytes: whichever way the entry leaves — swept after the
# grace, or legitimately TAKEN by the peer mid-grace — the same bytes
# credit ici_reclaimed_bytes exactly once, so the /device pinned
# estimate (leaked - reclaimed) cannot drift upward on delivered
# batches (guarded by _local_lock like the exchange itself)
_grace_uid_bytes: Dict[int, int] = {}


def _sweep_reclaim(now: Optional[float] = None) -> None:
    """Drop expired same-process exchange entries (called
    opportunistically from lane activity and close). Reclaimed bytes
    are counted (ici_reclaimed_bytes) so /device can show how much of
    the leaked estimate actually came back."""
    import time as _time
    now = _time.monotonic() if now is None else now
    freed = 0
    with _local_lock:
        while _reclaim_queue and _reclaim_queue[0][0] <= now:
            _, uid = _reclaim_queue.popleft()
            _local_exchange.pop(uid, None)
            # credit what close charged — even when the peer already
            # took the entry (its take credited it, pop above is a
            # no-op and the uid is gone from the ledger)
            freed += _grace_uid_bytes.pop(uid, 0)
    if freed:
        _reclaimed_bytes_counter.add(freed)


def leak_snapshot() -> dict:
    """The /device leak pane: what the lane has abandoned, what came
    back, and where the circuit breaker stands."""
    with _local_lock:
        by_epoch = len(_leaked_by_epoch)
        grace_queued = len(_reclaim_queue)
    leaked = _leaked_bytes_counter.get_value()
    reclaimed = _reclaimed_bytes_counter.get_value()
    return {
        "leaked_bytes": leaked,
        "reclaimed_bytes": reclaimed,
        "pinned_bytes_estimate": max(0, leaked - reclaimed),
        "leaked_pull_bytes": _leaked_pull_bytes[0],
        "unpulled_registrations": _unpulled_registrations.get_value(),
        "epochs_tracked": by_epoch,
        "grace_queue": grace_queued,
        "leak_cap_bytes": _LEAK_CAP_BYTES,
        "leak_global_cap_bytes": _LEAK_GLOBAL_CAP_BYTES,
        "pull_lane_tripped":
            _leaked_pull_bytes[0] >= _LEAK_GLOBAL_CAP_BYTES,
    }


def expose_ici_vars() -> None:
    """(Re-)expose the lane's bvars — called from Server.start like the
    socket counters (the PR 2 unexpose_all survival rule): a restarted
    server must not silently drop ici_* from /vars."""
    global _lane_status_var
    if _lane_status_var is not None:
        try:
            _lane_status_var.expose("ici_transfer_lane")
        except Exception:
            pass
    else:
        _publish_lane_status()
    for adder in _lazy_adders:
        adder.reexpose_counter()


def _encode_spec(a) -> bytes:
    dt = str(a.dtype).encode()
    parts = [struct.pack(">B", len(dt)), dt, struct.pack(">B", a.ndim)]
    if a.ndim:
        parts.append(struct.pack(f">{a.ndim}q", *a.shape))
    parts.append(struct.pack(">Q", a.nbytes))
    return b"".join(parts)


def _decode_spec(data: bytes, pos: int) -> Tuple[dict, int]:
    (dtlen,) = struct.unpack_from(">B", data, pos)
    pos += 1
    dtype = data[pos:pos + dtlen].decode()
    pos += dtlen
    (rank,) = struct.unpack_from(">B", data, pos)
    pos += 1
    shape = struct.unpack_from(f">{rank}q", data, pos) if rank else ()
    pos += 8 * rank
    (nbytes,) = struct.unpack_from(">Q", data, pos)
    pos += 8
    return {"dtype": dtype, "shape": tuple(shape), "nbytes": nbytes}, pos


def _encode_descriptor(uid: int, arrays) -> bytes:
    parts = [struct.pack(">QH", uid, len(arrays))]
    for a in arrays:
        parts.append(_encode_spec(a))
    return b"".join(parts)


def _decode_descriptor(data: bytes) -> Tuple[int, List[dict]]:
    uid, count = struct.unpack_from(">QH", data, 0)
    pos = 10
    specs = []
    for _ in range(count):
        spec, pos = _decode_spec(data, pos)
        specs.append(spec)
    return uid, specs


def _encode_coalesced(uid: Optional[int], batches) -> bytes:
    """F_COALESCED payload: N sub-batches in one frame. ``uid`` is the
    group's single registration (descriptor mode); None means staged
    mode (each sub-batch's numpy blob rides inline)."""
    if uid is None:
        parts = [struct.pack(">BH", 1, len(batches))]
        for arrays in batches:
            blob = _encode_device_batch(arrays)
            parts.append(struct.pack(">I", len(blob)))
            parts.append(blob)
    else:
        parts = [struct.pack(">BH", 0, len(batches)),
                 struct.pack(">Q", uid)]
        for arrays in batches:
            parts.append(struct.pack(">H", len(arrays)))
            for a in arrays:
                parts.append(_encode_spec(a))
    return b"".join(parts)


def _decode_coalesced(data: bytes):
    """-> ("staged", None, [blob, ...]) |
          ("pull", uid, [[spec, ...] per sub-batch])"""
    mode, count = struct.unpack_from(">BH", data, 0)
    pos = 3
    if mode == 1:
        blobs = []
        for _ in range(count):
            (ln,) = struct.unpack_from(">I", data, pos)
            pos += 4
            blobs.append(data[pos:pos + ln])
            pos += ln
        return "staged", None, blobs
    (uid,) = struct.unpack_from(">Q", data, pos)
    pos += 8
    groups = []
    for _ in range(count):
        (narr,) = struct.unpack_from(">H", data, pos)
        pos += 2
        specs = []
        for _ in range(narr):
            spec, pos = _decode_spec(data, pos)
            specs.append(spec)
        groups.append(specs)
    return "pull", uid, groups


class IciConn(Conn):
    """One ici:// connection: RdmaEndpoint's state machine re-expressed.

    Outbound items queue in FIFO (`_outq`) so a device-batch descriptor
    can never overtake — or be overtaken by — the app bytes of the RPC
    that references it; the window check happens at flush time on the
    queue head, so a stalled lane stalls everything behind it, exactly
    like the RDMA endpoint's window_size gate on the whole send queue
    (rdma_endpoint.h:235-241)."""

    supports_device_lane = True
    # Socket.write_device_payload passes a stage tracker through to the
    # flush/ack machinery (transport/device_stats.BatchTracker)
    supports_device_tracker = True

    def __init__(self, inner: TcpConn, local: EndPoint, remote: EndPoint,
                 recv_device_ordinal: int = 0,
                 window: int = DEFAULT_WINDOW,
                 pool: Optional[DeviceRecvPool] = None):
        self._inner = inner
        self._local = local
        self._remote = remote
        self._recv_device_ordinal = recv_device_ordinal
        self._window = window                    # credits we grant the peer
        self._pool = pool or _default_pool
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        # _pump is reached from the input-drain fiber (read_into) AND
        # from processing fibers (take_device_payload); the ingest state
        # (_inbuf/_appbuf/_lane/ack counters) needs one owner at a time
        self._pump_lock = threading.Lock()
        # outbound: FIFO of ("bytes", payload) | ("ctrl", ftype, payload)
        # | ("lane", arrays, tracker) — the tracker (device_stats stage
        # timeline, or None) rides the queue item so the flush/ack legs
        # never look anything up
        self._outq: Deque[Tuple] = deque()
        self._out_bytes = 0                      # backpressure accounting
        self._wirebuf = bytearray()              # framed, partially written
        # flush-stamp bookkeeping (all under _flush_lock): cumulative
        # bytes pushed into TCP, and (target_offset, tracker) marks —
        # a lane frame's tracker stamps lane_flushed when the wire
        # counter passes its frame's end
        self._wire_written = 0
        self._wire_marks: Deque[Tuple[int, object]] = deque()
        self._inbuf = bytearray()
        self._appbuf = bytearray()
        self._lane: Deque[Tuple] = deque()       # inbound batch descriptors
        self._closed_read = False
        self._closed = False
        # set when an unsendable batch is detected at flush time: every
        # later write/flush refuses, so no frame can follow the popped
        # poison item and the lane/envelope FIFO pairing stays intact
        # even if a channel catches the error and retries
        self._poisoned: Optional[str] = None
        # flow-control state (sender side)
        # flow-control state below is touched from the flush path (under
        # _flush_lock) AND the pump path (under _pump_lock) — it needs
        # its own lock, not either of those
        self._fc_lock = threading.Lock()
        self._sent = 0                           # device batches sent
        self._peer_acked = 0                     # cumulative acks from peer
        # byte budget: footprints of un-ACKed batches, FIFO (the peer
        # consumes lane batches in order), so bytes-in-flight is
        # derivable from the cumulative ack count; each entry is
        # (footprint, is_pull, tracker-or-None)
        self._inflight_footprints: Deque[Tuple[int, bool, object]] = \
            deque()
        self._inflight_bytes = 0
        # uids this connection registered for peer pull; reclaimed (or at
        # least counted) on close/failure
        self._issued_uids: List[int] = []
        self._pull_registered = 0                # await_pull count (no cancel)
        # flow-control state (receiver side)
        self._consumed = 0                       # batches we pulled
        self._acked_sent = 0                     # last consumed count sent
        # defer-flush window (hold_flush/release_flush): >0 means a
        # caller is batching enqueues (device batch + its envelope) and
        # will drain them in one gather-write at release
        self._hold_depth = 0
        self._flush_pending = False
        # adaptive window: last grant the peer rode on a bare ACK
        # (0 = none yet; effective window stays the hello window)
        self._peer_grant = 0
        # idle-ACK timer state (under _fc_lock) + lane counters
        self._idle_ack_armed = False
        self._idle_acks = 0
        self._coalesced_frames = 0
        self._coalesced_batches = 0
        # take-path caches: the recv device is fixed per conn (the
        # ordinal came from the endpoint), so jax.devices() and the
        # SingleDeviceSharding need resolving once, not per batch
        self._recv_dev = None
        self._recv_sharding = None
        # handshake
        self.peer_info: Optional[dict] = None
        self._hello_evt = threading.Event()
        self._want_writable = False
        self._on_writable_cb: Optional[Callable[[], None]] = None
        srv = _get_transfer_server()
        hello = {
            "proc": _PROC_UUID,
            "transfer_addr": srv.address() if srv is not None else None,
            "window": self._window,
            # advertised recv byte budget: the sender derives its
            # effective window from this. Like RDMA's per-connection
            # pre-posted rbufs (rdma_endpoint.h:235-241) it is a
            # PER-CONNECTION bound — window × the largest block class —
            # capped by the pool; aggregate pressure from many senders
            # still lands on the pool's blocking admission, exactly as
            # rbuf posting does when the block pool runs dry.
            # max_batch is the pool capacity: the largest single batch
            # the receiver could EVER admit (bigger ones are unsendable;
            # batches between budget and max_batch go out alone)
            "budget": min(self._pool.capacity,
                          self._window * BLOCK_CLASSES[-1]),
            "max_batch": self._pool.capacity,
            "device": recv_device_ordinal,
            "can_pull": srv is not None,
        }
        _dev_stats.global_device_stats().track_device_conn(self)
        self._enqueue(("ctrl", F_HELLO, json.dumps(hello).encode()))
        self._flush()

    # --------------------------------------------------------- outbound
    def _enqueue(self, item: Tuple) -> None:
        with self._lock:
            if self._closed:
                # close() flips this under the same lock BEFORE it
                # sweeps queued-batch trackers: an enqueue losing that
                # race must fail loudly, or its tracker would be in no
                # sweep list and the cell would never balance
                raise ConnectionError("ici conn closed")
            if self._out_bytes > _MAX_OUT:
                raise BlockingIOError("ici out-buffer full")
            self._outq.append(item)
            if item[0] == "bytes":
                self._out_bytes += len(item[1])

    def _frame(self, ftype: int, payload: bytes) -> bytes:
        # every frame piggybacks the cumulative consumed count — the
        # imm-data ACK of rdma_endpoint.h:138
        self._acked_sent = self._consumed
        return _HDR.pack(ftype, self._consumed, len(payload)) + payload

    @staticmethod
    def _batch_footprint(arrays) -> int:
        """The pool footprint the receiver will reserve for this batch
        (same size-class rounding as DeviceRecvPool)."""
        return sum(round_to_class(a.nbytes) for a in arrays)

    def _apply_peer_ack(self, ack: int) -> None:
        """Advance the cumulative-consumed count and retire the matching
        FIFO footprints (bytes-in-flight accounting). Retired batches'
        stage trackers settle AFTER _fc_lock drops — the settle touches
        the cell lock and submits the device span, neither of which
        belongs under flow-control state."""
        acked_trackers = []
        with self._fc_lock:
            while self._peer_acked < ack and self._inflight_footprints:
                fp, _, tracker = self._inflight_footprints.popleft()
                self._inflight_bytes -= fp
                self._peer_acked += 1
                if tracker is not None:
                    acked_trackers.append(tracker)
            self._peer_acked = max(self._peer_acked, ack)
        for tracker in acked_trackers:
            tracker.lane_acked()

    def _unsendable_reason(self, arrays) -> Optional[str]:
        """A batch no receiver state could ever admit (footprint over
        the peer's pool capacity — pool.reserve rejects those outright)
        must fail at the source, not wedge the lane. Returns the error
        text, or None when sendable / peer unknown."""
        max_batch = int((self.peer_info or {}).get("max_batch") or 0)
        if max_batch:
            need = self._batch_footprint(arrays)
            if need > max_batch:
                return (f"ici: device batch footprint {need}B exceeds "
                        f"the peer's pool capacity {max_batch}B — "
                        f"unsendable (split the batch or raise the "
                        f"peer's DeviceRecvPool capacity)")
        return None

    def _effective_window(self, info: dict) -> int:
        """The batch window actually gating sends: the peer's hello
        window, overridden by the adaptive grant it rode on a bare ACK
        (bounded to 4x the hello window so a corrupt grant can't blow
        the pipeline open)."""
        base = int(info.get("window", 1))
        grant = self._peer_grant
        if grant > 0:
            return max(1, min(grant, base * 4))
        return base

    def _lane_ready(self) -> bool:
        """May the queue-head device batch go out? Gates: hello received
        (QP up), batch window (adaptive — see _effective_window), and
        the peer's advertised byte budget — bytes in flight plus this
        batch must fit, so per-connection in-flight bytes can never
        exceed what the receiver advertised. A batch larger than the
        budget (but within the peer's pool capacity) goes out ALONE
        once the lane drains."""
        info = self.peer_info
        if info is None:
            return False                     # QP not up yet
        budget = int(info.get("budget") or 0)
        need = self._batch_footprint(self._outq[0][1])
        window = self._effective_window(info)
        with self._fc_lock:
            if (self._sent - self._peer_acked) >= window:
                return False
            if (budget and self._inflight_bytes + need > budget
                    and self._inflight_bytes > 0):
                return False
        return True

    def _stage_lane_frame(self, arrays, tracker=None) -> bytes:
        """Turn a lane batch into its wire frame, registering the arrays
        for peer pull (or falling back to the staged lane). The tracker
        stamps descriptor-encode done here (host-stage boundary) and
        rides the in-flight footprint FIFO to its ack."""
        info = self.peer_info or {}
        footprint = self._batch_footprint(arrays)
        if info.get("proc") == _PROC_UUID:
            # same process: in-memory registry; take() device_puts (D2D)
            uid = _next_uuid()
            with _local_lock:
                _local_exchange[uid] = list(arrays)
            self._issued_uids.append(uid)
            frame = self._frame(F_DESCRIPTOR, _encode_descriptor(uid, arrays))
            is_pull = False
            staged = False
        else:
            srv = _get_transfer_server()
            if srv is not None and info.get("can_pull") \
                    and _pull_lane_allowed(info.get("proc")):
                uid = _next_uuid()
                srv.await_pull(uid, list(arrays))
                self._issued_uids.append(uid)
                with self._fc_lock:
                    self._pull_registered += 1
                frame = self._frame(F_DESCRIPTOR,
                                    _encode_descriptor(uid, arrays))
                is_pull = True
                staged = False
            else:
                # degraded lane: host-staged numpy over the control stream
                frame = self._frame(F_STAGED, _encode_device_batch(arrays))
                is_pull = False
                staged = True
        if tracker is not None:
            tracker.lane_encoded(staged=staged)
        with self._fc_lock:
            self._inflight_footprints.append((footprint, is_pull, tracker))
            self._inflight_bytes += footprint
            self._sent += 1
        # NOTE: no _sweep_reclaim() here — the grace sweep runs on the
        # timer close() schedules, not on every staged frame (it was a
        # lock + clock read on the hottest path in the lane)
        return frame

    def _collect_coalesce(self, head: Tuple) -> Optional[List[Tuple]]:
        """Called under _lock with ``head`` (a lane item) just popped:
        pull additional SMALL lane batches out of _outq so the group
        rides ONE coalesced frame — one descriptor, one registration,
        one receiver-side reservation. Hoisting a later lane batch over
        interleaved byte frames is safe (a descriptor only has to
        precede its OWN envelope; the receiver matches batches to
        envelopes FIFO in descriptor order) — which is also why an
        INELIGIBLE lane batch stops the scan: lane batches must keep
        their relative order. Returns the extra items (already removed
        from _outq), or None."""
        limit = int(_flag("ici_coalesce_bytes"))
        nmax = int(_flag("ici_coalesce_max"))
        if limit <= 0 or nmax <= 1 or not self._outq:
            return None
        if sum(a.nbytes for a in head[1]) > limit:
            return None
        info = self.peer_info or {}
        budget = int(info.get("budget") or 0)
        window = self._effective_window(info)
        with self._fc_lock:
            slots = window - (self._sent - self._peer_acked) - 1
            room = (budget - self._inflight_bytes
                    - self._batch_footprint(head[1])) if budget else None
        if slots <= 0:
            return None
        extras: List[Tuple] = []
        keep: Deque[Tuple] = deque()
        while self._outq and len(extras) < nmax - 1 and slots > 0:
            it = self._outq.popleft()
            if it[0] != "lane":
                keep.append(it)
                continue
            fp = self._batch_footprint(it[1])
            if sum(a.nbytes for a in it[1]) > limit \
                    or (room is not None and fp > room) \
                    or self._unsendable_reason(it[1]) is not None:
                keep.append(it)
                break
            extras.append(it)
            slots -= 1
            if room is not None:
                room -= fp
        while self._outq:
            keep.append(self._outq.popleft())
        self._outq = keep
        return extras or None

    def _stage_coalesced_frame(self, items: List[Tuple]) -> bytes:
        """One F_COALESCED frame for N small lane batches: one uid /
        one pull registration / one receiver reservation for the whole
        group, while window, budget, and stage-tracker accounting stay
        per sub-batch (each still consumes one window slot, one ack)."""
        info = self.peer_info or {}
        batches = [it[1] for it in items]
        flat = [a for b in batches for a in b]
        staged = False
        is_pull = False
        if info.get("proc") == _PROC_UUID:
            uid = _next_uuid()
            with _local_lock:
                _local_exchange[uid] = flat
            self._issued_uids.append(uid)
            payload = _encode_coalesced(uid, batches)
        else:
            srv = _get_transfer_server()
            if srv is not None and info.get("can_pull") \
                    and _pull_lane_allowed(info.get("proc")):
                uid = _next_uuid()
                srv.await_pull(uid, flat)
                self._issued_uids.append(uid)
                with self._fc_lock:
                    self._pull_registered += 1
                payload = _encode_coalesced(uid, batches)
                is_pull = True
            else:
                staged = True
                payload = _encode_coalesced(None, batches)
        frame = self._frame(F_COALESCED, payload)
        for it in items:
            if it[2] is not None:
                it[2].lane_encoded(staged=staged)
        with self._fc_lock:
            for it in items:
                fp = self._batch_footprint(it[1])
                self._inflight_footprints.append((fp, is_pull, it[2]))
                self._inflight_bytes += fp
                self._sent += 1
            self._coalesced_frames += 1
            self._coalesced_batches += len(items)
        return frame

    def hold_flush(self) -> None:
        """Open a defer-flush window: while at least one hold is open,
        _flush() only notes that work is pending — the matching
        release_flush() drains everything in ONE gather-write. Channel
        and server dispatch hold across their lane_lock pairing (device
        batch + its envelope) so the TCP syscalls run OUTSIDE the lock
        instead of serializing every worker fiber on it."""
        with self._lock:
            self._hold_depth += 1

    def release_flush(self) -> None:
        with self._lock:
            self._hold_depth -= 1
            fire = self._hold_depth == 0 and self._flush_pending
            if fire:
                self._flush_pending = False
        if fire:
            drained = self._flush()
            # mirror _pump_locked's tail: a deferred flush that drains
            # a previously-stalled queue must still fire the writable
            # edge, or a parked keep_write fiber stays parked
            if drained and self._want_writable:
                self._want_writable = False
                cb = self._on_writable_cb
                if cb is not None:
                    cb()

    def _flush(self) -> bool:
        """Drain wirebuf + eligible queue items into TCP. Single-flight
        (two flushers would interleave framed bytes). True = all
        drained. Framing is a GATHER pass: every currently-sendable
        queue item is framed before each TCP write, so a burst pays one
        syscall, not one per item."""
        if self._poisoned is not None:
            raise ConnectionError(self._poisoned)
        if self._hold_depth > 0:
            with self._lock:
                if self._hold_depth > 0:
                    self._flush_pending = True
                    return False
        with self._flush_lock:
            while True:
                # re-check INSIDE the lock: a writer that passed the
                # outer check while another flusher was poisoning must
                # not drain its frame past the popped batch
                if self._poisoned is not None:
                    raise ConnectionError(self._poisoned)
                stalled = self._frame_ready_items()
                if not self._wirebuf:
                    return not stalled
                while self._wirebuf:
                    # the memoryview is released EXPLICITLY before the
                    # resize below: callee frames keep the view object
                    # alive in their locals, and a frame-walking sampler
                    # (the flight recorder) can briefly pin those frames
                    # — a refcount-implicit release would then race the
                    # `del` into "BufferError: Existing exports of data"
                    mv = memoryview(self._wirebuf)
                    try:
                        n = self._inner.write(mv)
                    except BlockingIOError:
                        self._inner.request_writable_event()
                        return False
                    finally:
                        mv.release()
                    del self._wirebuf[:n]
                    self._wire_written += n
                    while self._wire_marks and \
                            self._wire_marks[0][0] <= self._wire_written:
                        # this lane frame's bytes fully left for TCP:
                        # pump-flush waypoint (wire_us starts here)
                        self._wire_marks.popleft()[1].lane_flushed()
                if stalled:
                    return False

    def _frame_ready_items(self) -> bool:
        """Pop every currently-sendable _outq item and frame it into
        _wirebuf (the caller pays one TCP write for the lot — PR 4's
        gather-write idea applied to the lane). Adjacent small lane
        batches coalesce into one F_COALESCED frame. Returns True when
        the queue head is a credit-gated lane batch (caller parks for
        the ACK edge). Runs under _flush_lock."""
        while len(self._wirebuf) < _FLUSH_CHUNK:
            poison = None
            extras = None
            with self._lock:
                if not self._outq:
                    return False
                item = self._outq[0]
                if item[0] == "lane":
                    poison = self._unsendable_reason(item[1])
                    if poison is not None:
                        # poison the whole connection, not just the
                        # item: later writes must not slip past the
                        # popped batch or the receiver would FIFO-
                        # match some other RPC's arrays to this
                        # RPC's envelope
                        self._outq.popleft()
                        self._poisoned = poison
                    elif not self._lane_ready():
                        # out of credit: park until an ACK arrives
                        self._want_writable = True
                        return True
                    else:
                        self._outq.popleft()
                        extras = self._collect_coalesce(item)
                else:
                    self._outq.popleft()
                    if item[0] == "bytes":
                        self._out_bytes -= len(item[1])
            if poison is not None:
                if len(item) > 2 and item[2] is not None:
                    # the popped batch's tracker settles as failed
                    # (the span carries the unsendable reason)
                    item[2].lane_failed(poison)
                raise ConnectionError(poison)
            if item[0] == "bytes":
                self._wirebuf += self._frame(F_BYTES, item[1])
            elif item[0] == "ctrl":
                self._wirebuf += self._frame(item[1], item[2])
            elif extras:
                group = [item] + extras
                self._wirebuf += self._stage_coalesced_frame(group)
                end = self._wire_written + len(self._wirebuf)
                for it in group:
                    if it[2] is not None:
                        self._wire_marks.append((end, it[2]))
            else:                             # lone lane batch
                tracker = item[2]
                self._wirebuf += self._stage_lane_frame(item[1],
                                                        tracker)
                if tracker is not None:
                    self._wire_marks.append(
                        (self._wire_written + len(self._wirebuf),
                         tracker))
        return False

    def write(self, mv: memoryview) -> int:
        if self._poisoned is not None:
            raise ConnectionError(self._poisoned)
        data = bytes(mv)
        self._enqueue(("bytes", data))
        self._flush()
        return len(data)

    def write_device_payload(self, arrays, tracker=None) -> bool:
        """Stage jax arrays on our device and queue the batch. Host
        inputs are device_put once here (H2D staging); from then on the
        payload moves device-to-device only. ``tracker``: the
        device_stats stage timeline riding this batch (or None)."""
        jax = _jax()
        staged = []
        for a in arrays:
            if not isinstance(a, jax.Array):
                a = jax.device_put(a)
            staged.append(a)
        if self._poisoned is not None:
            if tracker is not None:
                tracker.lane_failed(self._poisoned)
            raise ConnectionError(self._poisoned)
        # fail-fast at the call site when the peer is already known
        # (otherwise flush-time detection poisons the connection)
        reason = self._unsendable_reason(staged)
        if reason is not None:
            if tracker is not None:
                tracker.lane_failed(reason)
            raise ConnectionError(reason)
        try:
            self._enqueue(("lane", staged, tracker))
        except (ConnectionError, BlockingIOError) as e:
            # closed-conn / out-buffer refusal: settle here — the batch
            # never entered a queue any sweep covers
            if tracker is not None:
                tracker.lane_failed(str(e))
            raise
        self._flush()
        return True

    # ---------------------------------------------------------- inbound
    def _pump(self) -> None:
        with self._pump_lock:
            fire = self._pump_locked()
        # the writable callback re-enters the write path (and a write
        # completion can pump again through read_into) — it must run
        # AFTER _pump_lock is released, never under it
        if fire is not None:
            fire()

    def _pump_locked(self) -> Optional[Callable[[], None]]:
        """Drain + decode inbound frames; returns the writable callback
        to fire once the caller has dropped _pump_lock (or None)."""
        buf = bytearray(256 << 10)
        while True:
            try:
                n = self._inner.read_into(memoryview(buf))
            except BlockingIOError:
                break
            if n == 0:
                self._closed_read = True
                break
            self._inbuf += buf[:n]
        window_opened = False
        while len(self._inbuf) >= _HDR.size:
            ftype, ack, length = _HDR.unpack_from(self._inbuf, 0)
            if length > _MAX_FRAME:
                raise ConnectionError(f"ici frame of {length}B exceeds max")
            if len(self._inbuf) < _HDR.size + length:
                break
            payload = bytes(self._inbuf[_HDR.size:_HDR.size + length])
            del self._inbuf[:_HDR.size + length]
            if ack > self._peer_acked:
                self._apply_peer_ack(ack)
                window_opened = True
            if ftype == F_BYTES:
                self._appbuf += payload
            elif ftype == F_DESCRIPTOR:
                uid, specs = _decode_descriptor(payload)
                self._lane.append(("pull", uid, specs))
            elif ftype == F_STAGED:
                self._lane.append(("staged", payload, None))
            elif ftype == F_COALESCED:
                mode, uid, subs = _decode_coalesced(payload)
                # one group dict shared by all sub-entries: the FIRST
                # take materializes the whole group (one pull / one
                # reservation), later takes just index into it
                group = {"mode": mode, "uid": uid, "subs": subs,
                         "out": None, "error": None}
                for i in range(len(subs)):
                    self._lane.append(("coal", group, i))
            elif ftype == F_HELLO:
                try:
                    self.peer_info = json.loads(payload.decode())
                except ValueError:
                    raise ConnectionError("ici: bad hello")
                self._hello_evt.set()
                window_opened = True          # lane may be gated on hello
            elif ftype == F_ACK:
                # header ack already applied; payload may carry the
                # receiver's adaptive window grant
                if len(payload) >= 4:
                    (grant,) = struct.unpack_from(">I", payload, 0)
                    self._peer_grant = grant
                    window_opened = True      # a wider grant may unpark
            else:
                raise ConnectionError(f"ici: unknown frame type {ftype}")
        if window_opened:
            drained = self._flush()
            if drained and self._want_writable:
                self._want_writable = False
                return self._on_writable_cb
        return None

    def read_into(self, mv: memoryview) -> int:
        self._pump()
        if self._appbuf:
            n = min(len(mv), len(self._appbuf))
            mv[:n] = self._appbuf[:n]
            del self._appbuf[:n]
            return n
        if self._closed_read:
            return 0
        raise BlockingIOError

    def _recv_device(self):
        """Resolved ONCE per conn: jax.devices() re-enumerates the
        client's device list per call, which the take path used to pay
        per batch."""
        dev = self._recv_dev
        if dev is None:
            devs = _jax().devices()
            k = self._recv_device_ordinal
            dev = devs[k] if 0 <= k < len(devs) else devs[0]
            self._recv_dev = dev
        return dev

    def _ack_grant_payload(self) -> bytes:
        """Adaptive window grant riding the bare-ACK payload: the
        receiver sizes the sender's pipeline from its own admission
        headroom (the input the sender's ack-stage reservoir reflects —
        ack latency is set by how deep the pipeline runs vs how fast
        takes drain it). Plenty of pool headroom -> grant 2x the hello
        window (deeper pipelining); pool under pressure -> shrink
        toward window/4 so the blocking admission gate, not the wire,
        is what backs off."""
        if not _flag("ici_adaptive_window"):
            return b""
        cap = self._pool.capacity or 1
        try:
            frac = self._pool.available / cap
        except Exception:
            frac = 1.0
        if frac >= 0.5:
            grant = self._window * 2
        elif frac >= 0.25:
            grant = self._window
        else:
            grant = max(1, self._window // 4)
        return struct.pack(">I", grant)

    def _maybe_send_ack(self) -> None:
        """Bare ACK once half the window is unacknowledged and no
        reverse-direction frame has carried it (SendAck,
        rdma_endpoint.h:138)."""
        if self._consumed - self._acked_sent >= max(1, self._window // 2):
            try:
                self._enqueue(("ctrl", F_ACK, self._ack_grant_payload()))
            except BlockingIOError:
                return      # out-buffer full: the ack piggybacks later
            except ConnectionError:
                # conn closed under us (a racing close flips _closed
                # before tearing down): a courtesy ack on a dying conn
                # is worthless — it must not error the batch the
                # caller already took successfully
                return
            self._flush()

    def _arm_idle_ack(self) -> None:
        """Eager-ACK timer: a quiescent conn must not leave its last
        consumed batches un-ACKed until close (acks normally piggyback
        on reverse traffic or fire at half-window). Armed from the take
        path; fires once, the next take re-arms. This is what lets the
        sender's /device cells balance WITHOUT a close(), and what
        reopens a ping-pong sender's window inside the same RTT."""
        if self._closed or self._consumed <= self._acked_sent:
            return
        delay = float(_flag("ici_idle_ack_ms")) / 1000.0
        if delay <= 0:
            return
        with self._fc_lock:
            if self._idle_ack_armed:
                return
            self._idle_ack_armed = True
        try:
            from brpc_tpu.fiber.timer import global_timer
            global_timer().schedule_after(delay, self._idle_ack_fire)
        except Exception:
            with self._fc_lock:
                self._idle_ack_armed = False

    def _idle_ack_fire(self) -> None:
        with self._fc_lock:
            self._idle_ack_armed = False
        if self._closed or self._consumed <= self._acked_sent:
            return          # a frame already carried the ack
        try:
            self._enqueue(("ctrl", F_ACK, self._ack_grant_payload()))
        except (BlockingIOError, ConnectionError):
            return
        self._idle_acks += 1
        try:
            self._flush()
        except Exception:
            pass            # conn poisoned/torn down under the timer

    def _sharding_for(self, target):
        if self._recv_sharding is None:
            self._recv_sharding = \
                _jax().sharding.SingleDeviceSharding(target)
        return self._recv_sharding

    def _take_local(self, uid: int, target) -> list:
        """Same-process take: pop the exchange entry, credit a grace-
        queued uid as DELIVERED, and device_put (the D2D/ICI hop)."""
        jax = _jax()
        with _local_lock:
            arrays = _local_exchange.pop(uid, None)
            # a grace-queued entry (sender closed) that the peer
            # legitimately takes is DELIVERED, not leaked: credit the
            # bytes its close charged
            grace_credit = _grace_uid_bytes.pop(uid, 0) \
                if arrays is not None else 0
        if grace_credit:
            _reclaimed_bytes_counter.add(grace_credit)
        if arrays is None:
            raise ConnectionError(
                "ici: same-process batch no longer available "
                "(sender closed and its registration was "
                "reclaimed)")
        return [a if (hasattr(a, "devices") and target in a.devices())
                else jax.device_put(a, target) for a in arrays]

    def _pull_arrays(self, uid: int, specs: List[dict], target) -> list:
        """Cross-process take: PjRt pull straight onto our device."""
        jax = _jax()
        info = self.peer_info or {}
        addr = _canonical_addr(info["transfer_addr"],
                               self._remote.host or "127.0.0.1")
        pconn = _get_pull_conn(addr)
        sharding = self._sharding_for(target)
        sds = [jax.ShapeDtypeStruct(
            s["shape"], _np_dtype(s["dtype"]),
            sharding=sharding) for s in specs]
        try:
            return pconn.pull(uid, sds)
        except BaseException:
            # a failed pull poisons the cached connection
            # (peer restart leaves a half-dead channel):
            # drop it so the next pull redials
            with _server_lock:
                if _conn_cache.get(addr) is pconn:
                    del _conn_cache[addr]
            raise

    def _materialize_coalesced(self, group: dict, target) -> List[list]:
        """First take of a coalesced group: ONE pool reservation for
        the whole group's footprint, one pull (or one exchange pop /
        one staged decode), then split back into per-sub-batch lists.
        The reservation is released when the LAST array of the group
        dies (GroupReservation refcount)."""
        jax = _jax()
        info = self.peer_info or {}
        if group["mode"] == "staged":
            subs = [_decode_device_batch(blob) for blob in group["subs"]]
            footprint = sum(round_to_class(x.nbytes)
                            for b in subs for x in b)
            res = self._pool.reserve_group(footprint)
            stager = _stager()
            try:
                outs = [[stager.land(x, device=target) for x in b]
                        for b in subs]
            except BaseException:
                self._pool.release(res)
                raise
        else:
            spec_groups = group["subs"]
            flat_specs = [s for g in spec_groups for s in g]
            footprint = sum(round_to_class(s["nbytes"])
                            for s in flat_specs)
            res = self._pool.reserve_group(footprint)
            try:
                if info.get("proc") == _PROC_UUID:
                    flat = self._take_local(group["uid"], target)
                else:
                    flat = self._pull_arrays(group["uid"], flat_specs,
                                             target)
                outs = []
                pos = 0
                for g in spec_groups:
                    outs.append(list(flat[pos:pos + len(g)]))
                    pos += len(g)
            except BaseException:
                self._pool.release(res)
                raise
        from brpc_tpu.butil.device_pool import GroupReservation
        holder = GroupReservation(self._pool, res,
                                  sum(len(o) for o in outs))
        for sub in outs:
            for arr in sub:
                self._pool.attach_group_finalizer(arr, holder)
        return outs

    def _take_coalesced(self, group: dict, idx: int, target) -> list:
        err = group.get("error")
        if err is not None:
            # a sibling's materialization failed: every sub-batch of
            # the group fails the same way (one registration, one fate)
            raise ConnectionError(err)
        outs = group.get("out")
        if outs is None:
            try:
                outs = self._materialize_coalesced(group, target)
            except BaseException as e:
                group["error"] = \
                    f"ici: coalesced group materialization failed: {e}"
                raise
            group["out"] = outs
        return outs[idx]

    def take_device_payload(self):
        # NO TCP pump here: a descriptor frame always precedes its
        # message's byte frames on the wire, so by the time the parser
        # saw those bytes the descriptor was already de-enveloped into
        # _lane. Pumping TCP from the parse path would steal the readable
        # edge — frames drained into _appbuf with the event already
        # consumed would never wake the input fiber again.
        with self._pump_lock:
            if not self._lane:
                return None
            kind, a, b = self._lane.popleft()
        jax = _jax()
        target = self._recv_device()
        if kind == "coal":
            out = self._take_coalesced(a, b, target)
            with self._pump_lock:
                self._consumed += 1
            self._maybe_send_ack()
            self._arm_idle_ack()
            return out
        footprints: List[int] = []
        try:
            # reserve inside the try: a partial multi-array reservation
            # must be released when a later reserve raises. BOTH lanes
            # reserve — the staged fallback is subject to the same HBM
            # admission control as the pull path (a peer without a
            # transfer server must not escape the budget).
            if kind == "staged":
                batch = _decode_device_batch(a)
                stager = _stager()
                for x in batch:
                    footprints.append(self._pool.reserve(x.nbytes))
                out = [stager.land(x, device=target) for x in batch]
            else:
                uid, specs = a, b
                info = self.peer_info or {}
                for s in specs:
                    footprints.append(self._pool.reserve(s["nbytes"]))
                if info.get("proc") == _PROC_UUID:
                    # same-process: receiver-driven device_put = the D2D
                    # copy (ICI hop on real multi-chip hardware)
                    out = self._take_local(uid, target)
                else:
                    out = self._pull_arrays(uid, specs, target)
        except BaseException:
            # admission timeout (MemoryError after reserve's 10s wait)
            # or pull failure: the error escapes into the input path,
            # which drops the CONNECTION — the batch is lost with it and
            # the sender learns through the conn failure + RPC retry,
            # the same resolution RDMA reaches when rbufs can't be
            # posted and the QP tears down
            for f in footprints:
                self._pool.release(f)
            raise
        for arr, f in zip(out, footprints):
            self._pool.attach_finalizer(arr, f)
        with self._pump_lock:
            self._consumed += 1
        self._maybe_send_ack()
        self._arm_idle_ack()
        return out

    # --------------------------------------------------------- plumbing
    def close(self) -> None:
        with self._lock:
            # under _lock: _enqueue checks the flag under the same
            # hold, so no batch can slip into _outq after the queued-
            # tracker sweep below has run
            if self._closed:
                return
            self._closed = True
        # best-effort flush: Socket's keep_write reported success for
        # frames that may still sit in _outq/_wirebuf behind a window
        # gate or TCP backpressure — don't silently drop them on close
        try:
            self._flush()
        except Exception:
            pass
        self._inner.close()
        # reclaim sender-side lane registrations. Same-process entries
        # go on a GRACE timer rather than being popped now: the flush
        # above may have just delivered their descriptors, and the peer
        # taking one after an instant pop would see a phantom error.
        # Cross-process await_pull registrations have no cancel API, so
        # the un-ACKed pull-registered batches are counted (an upper
        # bound: pulled-but-unacked ones are included) at
        # /vars ici_unpulled_registrations instead of pinning silently.
        import time as _time
        grace = _reclaim_grace_s()
        deadline = _time.monotonic() + grace
        queued = False
        grace_bytes = 0
        with _local_lock:
            for uid in self._issued_uids:
                arrays = _local_exchange.get(uid)
                if arrays is not None:
                    nb = sum(getattr(a, "nbytes", 0) or 0
                             for a in arrays)
                    _reclaim_queue.append((deadline, uid))
                    _grace_uid_bytes[uid] = nb
                    grace_bytes += nb
                    queued = True
        self._issued_uids.clear()
        if grace_bytes:
            # pinned until the grace sweep: counted leaked now, counted
            # reclaimed when the sweep drops them — the /device pane's
            # pinned estimate is the difference
            _leaked_bytes_counter.add(grace_bytes)
        if queued:
            # a timer guarantees the sweep even if no further lane
            # activity ever happens in this process (otherwise the
            # queued entries would pin device arrays until exit)
            try:
                from brpc_tpu.fiber.timer import global_timer
                global_timer().schedule_after(grace + 0.5,
                                              _sweep_reclaim)
            except Exception:
                pass
        # lane batches still QUEUED (window-gated, or stuck behind a
        # poisoned head) never reached _stage_lane_frame: no footprint
        # rides them, so the in-flight sweep below cannot see them —
        # their trackers settle here or the cell never balances and the
        # device span is stranded unsubmitted (collect under the lock,
        # settle after)
        with self._lock:
            queued_trackers = [item[2] for item in self._outq
                               if item[0] == "lane" and len(item) > 2
                               and item[2] is not None]
        for tracker in queued_trackers:
            tracker.lane_failed("connection closed before the batch "
                                "was flushed")
        with self._fc_lock:
            # every entry still in the deque is un-ACKed; only PULL-lane
            # batches pin peer-side registrations (staged/local bytes
            # attributed here would falsely trip the breaker)
            unacked = list(self._inflight_footprints)
            outstanding = sum(1 for _, p, _t in unacked if p)
            leaked_bytes = sum(fp for fp, p, _t in unacked if p)
        # un-ACKed batches' stage trackers settle as failures — a pull
        # registration the peer never drained is a LEAK and its device
        # span says so (leak-reclaim annotation + failed cell counter)
        peer_epoch = (self.peer_info or {}).get("proc")
        cross_proc = peer_epoch != _PROC_UUID
        for fp, is_pull, tracker in unacked:
            if tracker is not None:
                tracker.lane_failed(
                    "connection closed with batch un-ACKed"
                    + (" (pull registration pinned — no cancel API)"
                       if is_pull and cross_proc else ""),
                    leaked=is_pull and cross_proc)
        if outstanding > 0 and cross_proc:
            _unpulled_registrations.add(outstanding)
            _unpulled_bytes.add(leaked_bytes)
            _leaked_bytes_counter.add(leaked_bytes)
            with _local_lock:   # closes race from two threads' +=
                _note_leaked(peer_epoch, leaked_bytes)
        _sweep_reclaim()
        # drop any inbound descriptors never taken (their uids live in
        # the PEER's registry; our pool never reserved for them)
        with self._pump_lock:
            self._lane.clear()

    def start_events(self, on_readable: Callable[[], None],
                     on_writable: Callable[[], None]) -> None:
        self._on_writable_cb = on_writable

        def writable():
            if self._flush():
                on_writable()

        self._inner.start_events(on_readable, writable)

    def request_writable_event(self) -> None:
        # the stall may be TCP backpressure OR window credit; arm both
        # wake sources (whichever clears first fires on_writable once)
        self._want_writable = True
        self._inner.request_writable_event()

    def resume_read_events(self) -> None:
        resume = getattr(self._inner, "resume_read_events", None)
        if resume is not None:
            resume()

    @property
    def local_endpoint(self):
        return self._local

    @property
    def remote_endpoint(self):
        return self._remote

    # introspection for /connections and tests
    @property
    def lane_kind(self) -> str:
        info = self.peer_info or {}
        if info.get("proc") == _PROC_UUID:
            return "local-d2d"
        if info.get("can_pull") and _get_transfer_server() is not None:
            return "pjrt-pull"
        return "staged"

    @property
    def outstanding_batches(self) -> int:
        with self._fc_lock:
            return self._sent - self._peer_acked

    def lane_introspection(self) -> dict:
        """One /device conn row: credit-window occupancy, queue depths,
        buffered bytes — the live lane state next to the cells."""
        info = self.peer_info or {}
        window = int(info.get("window") or self._window)
        with self._fc_lock:
            outstanding = self._sent - self._peer_acked
            inflight_bytes = self._inflight_bytes
            sent = self._sent
            coalesced_frames = self._coalesced_frames
            coalesced_batches = self._coalesced_batches
        with self._lock:
            outq_depth = len(self._outq)
            out_bytes = self._out_bytes
        effective = self._effective_window(info) if info else window
        buffered = len(self._wirebuf) + len(self._inbuf) \
            + len(self._appbuf) + out_bytes
        return {
            "remote": str(self._remote),
            "lane_kind": self.lane_kind,
            "window": window,
            "effective_window": effective,
            "peer_grant": self._peer_grant,
            "outstanding_batches": outstanding,
            "window_occupancy": round(outstanding / effective, 3)
            if effective else 0.0,
            "inflight_bytes": inflight_bytes,
            "budget": int(info.get("budget") or 0),
            "batches_sent": sent,
            "coalesced_frames": coalesced_frames,
            "coalesced_batches": coalesced_batches,
            "idle_acks": self._idle_acks,
            "enqueue_depth": outq_depth,
            "buffered_bytes": buffered,
            "want_writable": self._want_writable,
            "poisoned": self._poisoned,
            "closed": self._closed,
        }


class _IciListener(Listener):
    def __init__(self, inner: Listener, ep: EndPoint):
        self._inner = inner
        self._ep = ep

    def stop(self) -> None:
        self._inner.stop()

    @property
    def endpoint(self) -> EndPoint:
        return self._ep


class IciTransport(Transport):
    scheme = "ici"

    def __init__(self, window: int = DEFAULT_WINDOW,
                 pool: Optional[DeviceRecvPool] = None):
        self._tcp = TcpTransport()
        self._window = window
        self._pool = pool

    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        # warm the transfer server HERE (caller thread): accepted conns are
        # constructed on the event-dispatcher thread, and a lazy multi-
        # second PjRt bring-up there would stall every socket in the process
        _get_transfer_server()
        ordinal = ep.device or 0
        tcp_ep = EndPoint("tcp", ep.host or "127.0.0.1", ep.port, ep.extras)
        ready = threading.Event()

        def wrap(conn: TcpConn):
            if not ready.wait(5):
                # listener bring-up stalled: fail the accepted conn
                # cleanly instead of NameError-ing on `bound` below
                conn.close()
                raise ConnectionError("ici: listener endpoint not bound "
                                      "within 5s; dropping accepted conn")
            on_new_conn(IciConn(conn, bound, conn.remote_endpoint,
                                recv_device_ordinal=ordinal,
                                window=self._window, pool=self._pool))

        inner = self._tcp.listen(tcp_ep, wrap)
        bound = EndPoint("ici", inner.endpoint.host, inner.endpoint.port,
                         ep.extras)
        ready.set()
        return _IciListener(inner, bound)

    def connect(self, ep: EndPoint) -> Conn:
        tcp_ep = EndPoint("tcp", ep.host, ep.port, ep.extras)
        inner = self._tcp.connect(tcp_ep)
        reply = ep.extra("reply_device")
        return IciConn(inner, inner.local_endpoint, ep,
                       recv_device_ordinal=int(reply) if reply else 0,
                       window=self._window, pool=self._pool)
