"""Transport plugin interface.

The reference hides three data planes behind one Socket (epoll TCP, verbs
RDMA, io_uring — SURVEY.md §2.4); we do the same behind ``Transport``:

  mem://  in-process loopback — the test fabric every layer above runs on
          (the reference's 127.0.0.1 fixture pattern, SURVEY.md §4)
  tcp://  real sockets via a selectors EventDispatcher (bootstrap + DCN)
  ici://  THE device data plane: TCP bootstrap handshake, PjRt pull-DMA
          device lane, windowed flow control (transport/ici.py — the
          RDMA slot)
  tpu://  in-process loopback variant of the device lane (test fabric)
  tpud:// staged (numpy-over-TCP) device lane — the degraded fallback
          ici:// uses when PjRt transfer is unavailable

A Conn is a non-blocking byte stream; BlockingIOError means "would block"
and the owning Socket parks until the dispatcher reports readiness.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from brpc_tpu.butil.endpoint import EndPoint


class Conn:
    """One established byte-stream connection (non-blocking)."""

    # True when the transport can move device arrays out of band (the
    # zero-copy lane); host-byte transports serialize payloads instead
    supports_device_lane: bool = False

    def write(self, mv: memoryview) -> int:
        """Write some bytes; raises BlockingIOError if none can be taken."""
        raise NotImplementedError

    def read_into(self, mv: memoryview) -> int:
        """Read some bytes; 0 = peer closed; raises BlockingIOError."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def start_events(self, on_readable: Callable[[], None],
                     on_writable: Callable[[], None]) -> None:
        """Begin edge-style readiness callbacks (may fire from any thread)."""
        raise NotImplementedError

    def request_writable_event(self) -> None:
        """Ask for one on_writable callback when the conn can take bytes
        again (epollout registration for a blocked writer)."""
        raise NotImplementedError

    # device-native transports may move jax arrays out of band; host-byte
    # transports leave this None
    def write_device_payload(self, arrays) -> Optional[object]:
        return None

    @property
    def local_endpoint(self) -> Optional[EndPoint]:
        return None

    @property
    def remote_endpoint(self) -> Optional[EndPoint]:
        return None


class Listener:
    def stop(self) -> None:
        raise NotImplementedError

    @property
    def endpoint(self) -> EndPoint:
        raise NotImplementedError


class Transport:
    scheme: str = ""

    def connect(self, ep: EndPoint) -> Conn:
        raise NotImplementedError

    def listen(self, ep: EndPoint, on_new_conn: Callable[[Conn], None]) -> Listener:
        raise NotImplementedError


_transports: Dict[str, Transport] = {}
_lock = threading.Lock()


def register_transport(t: Transport) -> None:
    with _lock:
        _transports[t.scheme] = t


def get_transport(scheme: str) -> Transport:
    t = _transports.get(scheme)
    if t is None:
        # lazy-register builtins on first use
        _register_builtins()
        t = _transports.get(scheme)
    if t is None:
        raise ValueError(f"no transport registered for scheme {scheme!r}")
    return t


def _register_builtins() -> None:
    with _lock:
        if "mem" not in _transports:
            from brpc_tpu.transport.mem import MemTransport
            _transports["mem"] = MemTransport()
        if "tcp" not in _transports:
            from brpc_tpu.transport.tcp import TcpTransport
            _transports["tcp"] = TcpTransport()
        if "tpu" not in _transports:
            from brpc_tpu.transport.tpu import TpuTransport
            _transports["tpu"] = TpuTransport()
        if "tpud" not in _transports:
            from brpc_tpu.transport.tpud import TpudTransport
            _transports["tpud"] = TpudTransport()
        if "ici" not in _transports:
            from brpc_tpu.transport.ici import IciTransport
            _transports["ici"] = IciTransport()
        if "ssl" not in _transports:
            from brpc_tpu.transport.ssl import SslTransport
            _transports["ssl"] = SslTransport()
