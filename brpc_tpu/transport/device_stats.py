"""Per-lane device telemetry: the measurement substrate under the
``tpu://`` / ``ici://`` data plane (the PR 7 cell discipline applied to
device transfers).

Every observability layer so far watches host traffic; this module
watches the DEVICE lane — the one the ROADMAP names weakest
(ici_headline 0.023 GB/s, ~2.4s p99, and nobody could say where the
seconds went). Each (peer, lane-kind) pair owns a stat cell:

  transfers / completed / failed balance (the chaos test's attribution
  invariant: ``transfers == completed + failed`` on every cell),
  staged-fallback count (pull lane degraded to host staging),
  bytes out/in with a decayed bytes-per-second window,
  a bounded transfer-latency reservoir (pooled on read, never averaged),
  and summed stage/wire/ack microseconds — the three-way attribution
  the stage-resolved device spans stamp per batch.

A transfer's life is carried by a :class:`BatchTracker` stamped at four
waypoints (the PR 3 span discipline, applied to the lane):

  t_submit   write_device_payload entered (host staging begins)
  t_encoded  descriptor encoded / arrays registered for pull (or the
             staged fallback serialized) — host-stage done
  t_flushed  the frame's bytes fully handed to the TCP socket
             (lane-enqueue + credit-window wait + pump-flush done)
  t_done     the peer's cumulative ACK covered this batch (wire +
             peer recv + ack return), or the loopback delivery

Derived: ``stage_us = t_encoded - t_submit``, ``wire_us = t_flushed -
t_encoded``, ``ack_us = t_done - t_flushed`` — summing to the transfer
latency BY CONSTRUCTION, so "this transfer was slow" becomes "it staged
/ it waited for credit / it sat on the wire". When rpcz is on, the
tracker also carries a child span of the owning RPC span (trace
inheritance through the channel / serving controller), so /rpcz shows
the device legs inside the call tree.

The thread-label hooks at the bottom (``stamp_device_thread`` /
``device_thread_label`` — deliberately UNIQUE verbs, the PR 11
``on_complete`` collision lesson) let the flight recorder attribute
device-poller and waiter-thread busy samples to ``device:<what>``
instead of losing them to thread-name leaves.

Cost gating: ``BRPC_TPU_DEVICE_STATS=0`` (env, read at import) or the
runtime flag ``device_stats_enabled`` turns the layer into one flag
check per transfer — ``device_stats_overhead_pct`` (bench + the
gate_device_obs smoke) is exactly on-vs-off throughput, gated <= 5%.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from brpc_tpu.butil.fast_rand import fast_rand_less_than
from brpc_tpu.butil.flags import define_flag, flag as _flag
from brpc_tpu.bvar.multi_dimension import MultiDimension
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.bvar.variable import Variable
from brpc_tpu.bvar.window import PerSecond

define_flag("device_stats_enabled",
            os.environ.get("BRPC_TPU_DEVICE_STATS", "1") != "0",
            "per-(peer, lane) device transfer stat cells + stage "
            "trackers (/device); BRPC_TPU_DEVICE_STATS=0 sets the "
            "default off for overhead A/B runs")
define_flag("device_probe_path", "DEVICE_PROBE.json",
            "path (cwd-relative) of the last tools/device_probe.py "
            "artifact surfaced on /device; empty disables the pane")

# a runaway caller (a conn per request) must degrade to a bounded
# table, not an unbounded registry — overflow lands on one cell
MAX_CELLS = 1024
_OVERFLOW_KEY = ("_overflow", "_overflow")


def enabled() -> bool:
    return _flag("device_stats_enabled")


def peer_key(ep) -> str:
    """Canonical peer label: scheme://host:port with extras stripped
    (``#device=K`` variants of one peer must land on ONE row)."""
    scheme = getattr(ep, "scheme", None)
    if scheme is not None:
        port = getattr(ep, "port", 0)
        return f"{scheme}://{getattr(ep, 'host', '')}" + \
            (f":{port}" if port else "")
    return str(ep)


class DeviceCell(Variable):
    """One (peer, lane-kind) stat cell. Counter discipline: every
    ``transfers`` increment is matched by exactly one ``completed`` or
    ``failed`` increment; receive-side counters (``recv_transfers`` /
    ``bytes_in``) sit outside that balance. Single lock + bounded
    reservoir (the BackendCell discipline — a composed LatencyRecorder
    costs ~4x on a per-transfer path); decayed bytes/s rides one
    Adder + PerSecond."""

    SAMPLE_CAP = 256

    __slots__ = ("_lock", "_bytes_var", "_bps", "transfers", "completed",
                 "failed", "staged_fallbacks", "recv_transfers",
                 "bytes_out", "bytes_in", "leaked_batches", "leaked_bytes",
                 "stage_us_sum", "wire_us_sum", "ack_us_sum",
                 "recv_us_sum", "_samples", "_nsampled", "_max_us")

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._bytes_var = Adder(0)
        self._bps = PerSecond(self._bytes_var)
        self.transfers = 0
        self.completed = 0
        self.failed = 0
        self.staged_fallbacks = 0
        self.recv_transfers = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.leaked_batches = 0
        self.leaked_bytes = 0
        self.stage_us_sum = 0.0
        self.wire_us_sum = 0.0
        self.ack_us_sum = 0.0
        self.recv_us_sum = 0.0
        self._samples: List[float] = []
        self._nsampled = 0
        self._max_us = 0.0

    # ------------------------------------------------------------ updates
    def note_open(self, nbytes: int) -> None:
        with self._lock:
            self.transfers += 1
            self.bytes_out += nbytes

    def note_done(self, stage_us: float, wire_us: float, ack_us: float,
                nbytes: int, failed: bool, leaked: bool = False) -> None:
        total = stage_us + wire_us + ack_us
        with self._lock:
            if failed:
                self.failed += 1
                if leaked:
                    self.leaked_batches += 1
                    self.leaked_bytes += nbytes
            else:
                self.completed += 1
            self.stage_us_sum += stage_us
            self.wire_us_sum += wire_us
            self.ack_us_sum += ack_us
            if total > self._max_us:
                self._max_us = total
            n = self._nsampled
            self._nsampled = n + 1
            s = self._samples
            if len(s) < self.SAMPLE_CAP:
                s.append(total)
            else:
                i = fast_rand_less_than(n + 1)
                if i < self.SAMPLE_CAP:
                    s[i] = total
        if not failed:
            self._bytes_var.add(nbytes)   # thread-local; outside the lock

    def note_recv(self, dur_us: float, nbytes: int) -> None:
        with self._lock:
            self.recv_transfers += 1
            self.bytes_in += nbytes
            self.recv_us_sum += dur_us
        self._bytes_var.add(nbytes)

    # ------------------------------------------------------------- reads
    def samples(self, limit: int = 256) -> List[float]:
        with self._lock:
            return self._samples[:limit]

    @staticmethod
    def _pick(sorted_samples: List[float], ratio: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(len(sorted_samples) - 1,
                  int(ratio * len(sorted_samples)))
        return sorted_samples[idx]

    def get_value(self) -> dict:
        with self._lock:
            s = sorted(self._samples)
            done = self.completed + self.failed
            total_us = self.stage_us_sum + self.wire_us_sum \
                + self.ack_us_sum
            out = {
                "transfers": self.transfers,
                "completed": self.completed,
                "failed": self.failed,
                "staged_fallbacks": self.staged_fallbacks,
                "recv_transfers": self.recv_transfers,
                "bytes_out": self.bytes_out,
                "bytes_in": self.bytes_in,
                "leaked_batches": self.leaked_batches,
                "leaked_bytes": self.leaked_bytes,
                "count": done,
                "stage_us_sum": round(self.stage_us_sum, 1),
                "wire_us_sum": round(self.wire_us_sum, 1),
                "ack_us_sum": round(self.ack_us_sum, 1),
                "recv_us_sum": round(self.recv_us_sum, 1),
                "latency_avg_us": round(total_us / done, 1) if done
                else 0.0,
                "max_latency_us": self._max_us,
            }
        out["bytes_per_second"] = self._bps.get_value()
        out["latency_p50_us"] = self._pick(s, 0.5)
        out["latency_p99_us"] = self._pick(s, 0.99)
        return out


class _DeviceDim(MultiDimension):
    """The labeled family with a JSON-safe get_value (the /vars dump
    json.dumps's the value; tuple keys would raise) — prometheus reads
    labels through ``labeled_items()`` so ``device_stats_*{peer=,lane=}``
    series stay properly labeled."""

    def get_value(self) -> Dict[str, object]:
        with self._lock:
            items = list(self._stats.items())
        return {"|".join(k): v.get_value() for k, v in items}


class BatchTracker:
    """One device batch's stage timeline, riding the lane queue item
    through the conn (the PR 7 'cell rides the record' discipline — the
    completion paths never touch the registry). Stamps are sequenced by
    the transfer pipeline (submit -> encode -> flush -> ack), only the
    finish races (ack vs close-leak) — settled under the cell lock."""

    __slots__ = ("cell", "span", "nbytes", "t_submit", "t_encoded",
                 "t_flushed", "staged", "_finished")

    def __init__(self, cell: DeviceCell, span, nbytes: int):
        self.cell = cell
        self.span = span
        self.nbytes = nbytes
        self.t_submit = time.monotonic_ns()
        self.t_encoded = 0
        self.t_flushed = 0
        self.staged = False
        self._finished = False

    # stamp verbs are deliberately unique across the tree (lock-model
    # unique-method fallback: a shared name would mint false call edges).
    # Stamps run their WHOLE body under the cell lock — the same lock
    # _settle's latch lives under — so a stamp and a settle serialize:
    # once _settle wins the latch (peer ack on the pump thread can land
    # between the TCP write returning and the flush mark firing), no
    # stamp can touch the already-submitted span, and a stamp that wins
    # finishes its span writes before the settle can submit.
    def lane_encoded(self, staged: bool = False) -> None:
        if self.span is None and not staged:
            # rpcz off and nothing to count: the stamp is a plain int
            # store the latch exists to protect SPAN writes from — a
            # settle racing it at worst reads the old value and books
            # those microseconds to the neighboring stage bucket. The
            # lock here was the hot path's single biggest tax.
            self.t_encoded = time.monotonic_ns()
            return
        with self.cell._lock:
            if self._finished:
                return
            self.t_encoded = time.monotonic_ns()
            if staged:
                self.staged = True
                self.cell.staged_fallbacks += 1   # lock already held
                if self.span is not None:
                    self.span.annotate("staged_fallback (pull lane "
                                       "unavailable or breaker-tripped)")
            if self.span is not None:
                self.span.write_done_us = self.t_encoded // 1000

    def lane_flushed(self) -> None:
        if self.span is None:
            # same span-less fast path as lane_encoded
            self.t_flushed = time.monotonic_ns()
            return
        with self.cell._lock:
            if self._finished:
                return
            self.t_flushed = time.monotonic_ns()
            if self.span is not None:
                self.span.first_byte_us = self.t_flushed // 1000
                self.span.annotate(
                    "pump-flush: frame handed to transport")

    def lane_acked(self) -> None:
        self._settle(failed=False)

    def lane_failed(self, reason: str, leaked: bool = False) -> None:
        self._settle(failed=True, leaked=leaked, reason=reason)

    def _settle(self, failed: bool, leaked: bool = False,
                reason: Optional[str] = None) -> None:
        cell = self.cell
        with cell._lock:
            if self._finished:
                return
            self._finished = True
        # annotate AFTER winning the latch: a second failure report
        # (conn check + socket wrapper both fire on one raise) must not
        # mutate a span already submitted to the rpcz ring
        if reason is not None and self.span is not None:
            self.span.annotate(("leak-reclaim: " if leaked else "") +
                               str(reason)[:200])
        now = time.monotonic_ns()
        enc = self.t_encoded or now
        flu = self.t_flushed or enc
        stage_us = max(0.0, (enc - self.t_submit) / 1e3)
        wire_us = max(0.0, (flu - enc) / 1e3)
        ack_us = max(0.0, (now - flu) / 1e3)
        cell.note_done(stage_us, wire_us, ack_us, self.nbytes, failed,
                     leaked=leaked)
        span = self.span
        if span is not None:
            from brpc_tpu.rpc import span as _span_mod
            span.end_us = now // 1000
            if failed:
                span.error_code = span.error_code or 1009  # EFAILEDSOCKET
            span.annotate(f"stage_us={stage_us:.0f} wire_us={wire_us:.0f} "
                          f"ack_us={ack_us:.0f}"
                          + (" staged" if self.staged else ""))
            _span_mod.submit_span(span)


class DeviceStats:
    """Process-wide registry: the labeled cell family plus a weak set
    of live device-lane conns (credit/queue introspection for the
    /device page)."""

    def __init__(self):
        self._dim = _DeviceDim(("peer", "lane"), DeviceCell)
        self._conns: "weakref.WeakSet" = weakref.WeakSet()
        self._conn_lock = threading.Lock()

    def device_cell(self, peer: str, lane: str) -> DeviceCell:
        key = (peer, lane)
        if not self._dim.has_stats(key) \
                and self._dim.count_stats() >= MAX_CELLS:
            key = _OVERFLOW_KEY
        return self._dim.get_stats(key)

    def rows(self) -> List[Tuple[Tuple[str, str], DeviceCell]]:
        return [(k, self._dim.get_stats(k))
                for k in self._dim.list_stats()]

    def track_device_conn(self, conn) -> None:
        # serialized against the census walk (WeakSet mutates during
        # iteration raise RuntimeError — the socket registry learned
        # this the hard way)
        with self._conn_lock:
            self._conns.add(conn)

    def device_conn_rows(self) -> List[dict]:
        with self._conn_lock:
            conns = list(self._conns)
        rows = []
        for c in conns:
            try:
                rows.append(c.lane_introspection())
            except Exception:
                continue
        return rows


_registry: Optional[DeviceStats] = None
_registry_lock = threading.Lock()


def global_device_stats() -> DeviceStats:
    global _registry
    reg = _registry
    if reg is None:
        with _registry_lock:
            if _registry is None:
                _registry = DeviceStats()
                _registry._dim.expose("device_stats")
            reg = _registry
    return reg


def expose_device_vars() -> None:
    """(Re-)expose the labeled family — called from Server.start like
    the socket counters, surviving a test fixture's unexpose_all."""
    global_device_stats()._dim.expose("device_stats")


# ------------------------------------------------------- transfer hooks

def open_transfer(peer: str, lane: str, nbytes: int,
                  parent_span=None,
                  cell: Optional[DeviceCell] = None) -> \
        Optional[BatchTracker]:
    """One tracker per outbound device batch; None when the layer is
    disabled (the single flag check the hot path pays). Callers on the
    per-transfer hot path pass their cached ``cell``
    (Socket._dev_send) to skip the registry lookup."""
    if not enabled():
        return None
    if cell is None:
        cell = global_device_stats().device_cell(peer, lane)
    cell.note_open(nbytes)
    span = None
    if parent_span is not None:
        from brpc_tpu.rpc.span import start_device_span
        span = start_device_span(parent_span, peer, lane)
        span.request_size = nbytes
    return BatchTracker(cell, span, nbytes)


# ----------------------------------------------- flight-recorder labels
#
# Threads that do device work outside any fiber (the device poller's
# pump, per-wait PjRt waiter threads, ici pump legs sampled with no
# serving context) stamp a label here; the flight recorder's sampler
# reads it through ``device_thread_label`` (bound at module load on the
# recorder side — the PR 8 sampler-lazy-import hazard). Plain dict +
# GIL-atomic ops: the sampler only reads.

_thread_labels: Dict[int, str] = {}


def stamp_device_thread(label: str, tid: Optional[int] = None) -> None:
    _thread_labels[tid if tid is not None
                   else threading.get_ident()] = label


def unstamp_device_thread(tid: Optional[int] = None) -> None:
    _thread_labels.pop(tid if tid is not None
                       else threading.get_ident(), None)


def device_thread_label(tid: int) -> Optional[str]:
    return _thread_labels.get(tid)


# --------------------------------------------------------------- pages

def _probe_pane() -> Optional[dict]:
    """The last device-probe artifact (tools/device_probe.py --out),
    bounded to the operator-relevant keys."""
    path = _flag("device_probe_path")
    if not path:
        return None
    try:
        if os.path.getsize(path) > (4 << 20):
            return {"error": "probe artifact too large to surface"}
        import json
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    pane = {k: doc[k] for k in
            ("headline_GBps", "p50_us", "p99_us", "lane_kind",
             "link_floor_us", "d2h_floor_us", "stage_breakdown",
             "device_stats_overhead_pct", "ici_stage_attribution_pct",
             "error", "lane_error", "bringup") if k in doc}
    try:
        pane["age_s"] = round(time.time() - os.path.getmtime(path), 1)
    except OSError:
        pass
    return pane or None


def device_page_payload(server=None, samples: int = 128) -> dict:
    """The /device payload, shared by the HTTP route and the builtin
    RPC service (one builder, two views that cannot diverge). The page
    is PROCESS-global (``server`` is accepted for builder-signature
    parity with the other pages and unused — transfers aren't owned by
    one server). Cells carry bounded raw latency reservoirs for
    cross-node pooling (tools/cluster_top.py); lane state / leak
    counters come straight from transport/ici.py when that lane is
    loaded."""
    import sys
    reg = global_device_stats()
    cells: Dict[str, dict] = {}
    totals = {"transfers": 0, "completed": 0, "failed": 0,
              "staged_fallbacks": 0, "recv_transfers": 0,
              "bytes_out": 0, "bytes_in": 0, "leaked_bytes": 0}
    for (peer, lane), cell in reg.rows():
        row = cell.get_value()
        row["latency_samples"] = cell.samples(samples)
        cells[f"{peer}|{lane}"] = row
        for k in totals:
            totals[k] += row.get(k, 0)
    out: dict = {
        "enabled": enabled(),
        "cells": cells,
        "totals": totals,
        "conns": reg.device_conn_rows(),
    }
    ici = sys.modules.get("brpc_tpu.transport.ici")
    if ici is not None:
        out["transfer_lane"] = ici.transfer_lane_status()
        pool = ici._default_pool
        out["recv_pool"] = {"capacity": pool.capacity, "used": pool.used,
                            "reserved_blocks": list(pool.reserved_blocks)}
        out["leaks"] = ici.leak_snapshot()
    else:
        out["transfer_lane"] = "not loaded"
    probe = _probe_pane()
    if probe is not None:
        out["probe"] = probe
    return out


def merge_device_payloads(payloads: List[dict]) -> dict:
    """The supervisor's group-wide /device view: per-shard payloads
    merged — counters sum, latency samples POOL (never averaged
    percentiles), conn panes concat, lane status = worst reading."""
    out: dict = {"mode": "shard_group", "shards_reporting": len(payloads),
                 "enabled": any(p.get("enabled") for p in payloads)}
    cells: Dict[str, dict] = {}
    pooled: Dict[str, List[float]] = {}
    totals: Dict[str, int] = {}
    conns: List[dict] = []
    lane_status: List[str] = []
    leaks: Dict[str, int] = {}
    for p in payloads:
        for key, row in (p.get("cells") or {}).items():
            m = cells.setdefault(key, {})
            for k, v in row.items():
                if k == "latency_samples":
                    pooled.setdefault(key, []).extend(v or ())
                elif k.startswith("max"):
                    if isinstance(v, (int, float)):
                        m[k] = max(m.get(k, 0), v)
                elif isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    m[k] = m.get(k, 0) + v
        for k, v in (p.get("totals") or {}).items():
            totals[k] = totals.get(k, 0) + (v or 0)
        conns.extend(p.get("conns") or ())
        if p.get("transfer_lane"):
            lane_status.append(p["transfer_lane"])
        for k, v in (p.get("leaks") or {}).items():
            if isinstance(v, (int, float)):
                leaks[k] = leaks.get(k, 0) + v
    for key, m in cells.items():
        s = sorted(pooled.get(key, ()))
        m["latency_p50_us"] = DeviceCell._pick(s, 0.5)
        m["latency_p99_us"] = DeviceCell._pick(s, 0.99)
        # bound the re-exported reservoir by EVEN STRIDE over the
        # sorted pool — keeping the head would hand a downstream
        # pooler a tail-less set whose "p99" is really ~p12
        if len(s) > 256:
            step = len(s) / 256.0
            m["latency_samples"] = [s[int(i * step)] for i in range(256)]
        else:
            m["latency_samples"] = s
        done = (m.get("completed", 0) or 0) + (m.get("failed", 0) or 0)
        tot = (m.get("stage_us_sum", 0) or 0) + \
            (m.get("wire_us_sum", 0) or 0) + (m.get("ack_us_sum", 0) or 0)
        m["latency_avg_us"] = round(tot / done, 1) if done else 0.0
    out["cells"] = cells
    out["totals"] = totals
    out["conns"] = conns
    out["leaks"] = leaks
    # worst real reading wins: a genuine "down:" beats everything, but
    # a host-only shard's "not loaded" must not mask a sibling whose
    # pull lane is genuinely up
    down = [s for s in lane_status if s.startswith("down")]
    if down:
        out["transfer_lane"] = down[0]
    elif "up" in lane_status:
        out["transfer_lane"] = "up"
    else:
        out["transfer_lane"] = lane_status[0] if lane_status \
            else "not loaded"
    return out


# -------------------------------------------------------- fork hygiene

def _postfork_reset() -> None:
    """Fork hygiene: every cell describes PARENT-side transfers on
    conns the child does not own, and the conn weak-set points into the
    parent's transport; a forked shard starts its device view from
    zero."""
    global _registry, _registry_lock, _thread_labels
    _registry = None
    _registry_lock = threading.Lock()
    _thread_labels = {}


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("transport.device_stats", _postfork_reset)


# --------------------------------------------------------------- census

def _device_census() -> dict:
    """Resource census: the HBM-recv budget in use plus the bytes the
    lane's staging/wire buffers and cell reservoirs hold — so /census
    totals include device memory (the PR 6 accounting discipline)."""
    import sys
    count = 0
    nbytes = 0
    reg = _registry
    if reg is not None:
        for _, cell in reg.rows():
            nbytes += len(cell.samples(1024)) * 8
        for row in reg.device_conn_rows():
            count += 1
            nbytes += row.get("buffered_bytes", 0) or 0
    ici = sys.modules.get("brpc_tpu.transport.ici")
    if ici is not None:
        pool = ici._default_pool
        nbytes += pool.used
        count += sum(pool.reserved_blocks)
    return {"count": count, "bytes": nbytes}


from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the registry it measures)

_census.register("device_lane", _device_census)
