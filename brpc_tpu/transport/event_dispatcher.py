"""EventDispatcher: readiness poller for fd-based transports
(brpc/event_dispatcher.h:32 — epoll/kqueue there, selectors here).

One thread runs the selector; callbacks fire on it and must be cheap —
they schedule fibers and return (the reference's edge-trigger handlers do
the same: StartInputEvent only bumps an atomic and maybe spawns a bthread).
Write-readiness registrations are one-shot (epollout for blocked writers).
"""

from __future__ import annotations

import os
import selectors
import socket as pysocket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.bvar.reducer import Adder, Maxer, PassiveStatus

# event-loop stall instrumentation (the flight recorder's watchdog
# half): the longest time one wakeup's callback batch held the event
# thread, over the sampler's 10s window. The dispatcher stamps tick
# start/end (two clock reads per non-empty batch); completed ticks
# update the Maxer here, in-progress ticks are caught by the flight
# recorder's sampler thread (note_stall), which sees a handler
# monopolizing the event thread BEFORE the tick ever completes.
_tick_ms_max = Maxer()
# ticks that overran the dispatcher_stall_ms budget (flight_recorder
# annotates the serving rpcz span when it catches one live)
nstalls = Adder()
_stall_win = None
_stall_win_lock = threading.Lock()


def _stall_window():
    """Windowed view over the tick-duration Maxer, created on first
    scrape (a Window registers with the background sampler thread).
    Locked double-check: a LOSING racer's Window would stay registered
    with the sampler forever and drain the delta-mode Maxer via
    reset() each tick, zeroing the kept window's samples."""
    global _stall_win
    if _stall_win is None:
        with _stall_win_lock:
            if _stall_win is None:
                from brpc_tpu.bvar.window import Window
                _stall_win = Window(_tick_ms_max, 10)
    return _stall_win


def stall_ms_max_10s() -> float:
    """Max tick duration over the sampler window, INCLUDING the
    current not-yet-sampled tick value (the bvar sampler snapshots
    1/s; a stall must be visible the moment it is recorded, not up to
    a second later)."""
    win = _stall_window().get_value() or 0.0
    live = _tick_ms_max.get_value() or 0.0
    return round(max(win, live), 3)


_stall_var = PassiveStatus(stall_ms_max_10s)


def expose_stall_vars() -> None:
    """(Re-)expose the watchdog bvars — called at import and again
    from Server.start, surviving a test fixture's unexpose_all like
    the other socket/scheduler counters."""
    nstalls.expose("dispatcher_stalls")
    _stall_var.expose("dispatcher_stall_ms_max_10s")


expose_stall_vars()


def note_stall(ms: float) -> None:
    """Record an in-progress tick overrun observed by the sampler."""
    _tick_ms_max.update(ms)


class EventDispatcher:
    def __init__(self, name: str = "event_dispatcher"):
        self._selector = selectors.DefaultSelector()
        self._lock = threading.Lock()
        # fd -> [on_readable, on_writable(one-shot), armed_read_mask,
        #        oneshot_read]
        self._handlers: Dict[int, list] = {}
        self._wakeup_r, self._wakeup_w = pysocket.socketpair()
        self._wakeup_r.setblocking(False)
        self._selector.register(self._wakeup_r, selectors.EVENT_READ, None)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._name = name
        # tick telemetry for the stall watchdog: _tick_start_ns is
        # nonzero exactly while this wakeup's callback batch runs on
        # the event thread; _tick_seq disambiguates ticks so the
        # watchdog annotates each overrun once
        self._tick_start_ns = 0
        self._tick_seq = 0
        # epoll interest changes take effect while another thread sits
        # in epoll_wait — pause/resume need no wakeup-pipe kick there
        # (one write + one dispatcher wake per call otherwise; the
        # pluck lane pays that pair per sync RPC). Select/poll-backed
        # selectors snapshot their fd set per call and DO need the kick.
        self._rearm_needs_wakeup = not isinstance(
            self._selector, getattr(selectors, "EpollSelector", ()))

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run, name=self._name,
                                            daemon=True)
            self._thread.start()

    def _wakeup(self):
        # registry changes made FROM the dispatcher thread (inline
        # processing re-arming reads mid-event) need no pipe write: the
        # loop re-enters select() right after the callback returns
        if threading.current_thread() is self._thread:
            return
        try:
            self._wakeup_w.send(b"x")
        except (BlockingIOError, OSError):
            pass

    def add_consumer(self, fd: int, on_readable: Callable[[], None],
                     oneshot_read: bool = False) -> None:
        """Register read-readiness callbacks for fd.

        ``oneshot_read=True`` gives edge-trigger-style semantics: after a
        read event fires, read interest is DISARMED until the consumer
        calls resume_read(fd) (typically once its drain hits EAGAIN).
        Level-triggered polling would otherwise spin the dispatcher for
        the whole time a drain fiber works through a bulk transfer —
        the reason the reference uses EPOLLET (event_dispatcher.h:32)."""
        with self._lock:
            self._handlers[fd] = [on_readable, None, selectors.EVENT_READ,
                                  oneshot_read]
            try:
                self._selector.register(fd, selectors.EVENT_READ, fd)
            except KeyError:
                self._selector.modify(fd, selectors.EVENT_READ, fd)
            self._ensure_thread()
        self._wakeup()

    def pause_read(self, fd: int) -> None:
        """Drop read interest until resume_read (level-triggered
        consumers use this for busy periods, so pending data doesn't
        spin the select loop while a handler is parked)."""
        with self._lock:
            h = self._handlers.get(fd)
            if h is None or not (h[2] & selectors.EVENT_READ):
                return
            h[2] &= ~selectors.EVENT_READ
            mask = h[2] | (selectors.EVENT_WRITE if h[1] else 0)
            try:
                if mask:
                    self._selector.modify(fd, mask, fd)
                else:
                    self._selector.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
        if self._rearm_needs_wakeup:
            self._wakeup()

    def resume_read(self, fd: int) -> None:
        """Re-arm read interest after a one-shot read fire (safe to call
        when already armed or after remove_consumer)."""
        with self._lock:
            h = self._handlers.get(fd)
            if h is None or h[2] & selectors.EVENT_READ:
                return
            h[2] |= selectors.EVENT_READ
            mask = h[2] | (selectors.EVENT_WRITE if h[1] else 0)
            try:
                self._selector.modify(fd, mask, fd)
            except (KeyError, ValueError, OSError):
                try:
                    self._selector.register(fd, mask, fd)
                except (KeyError, ValueError, OSError):
                    return
        if self._rearm_needs_wakeup:
            self._wakeup()

    def request_writable(self, fd: int, on_writable: Callable[[], None]) -> None:
        """One-shot write-readiness callback (the epollout dance the
        reference does for connecting/blocked sockets)."""
        with self._lock:
            h = self._handlers.get(fd)
            if h is None:
                self._handlers[fd] = [None, on_writable, 0, False]
                self._selector.register(fd, selectors.EVENT_WRITE, fd)
            else:
                h[1] = on_writable
                mask = h[2] | selectors.EVENT_WRITE
                try:
                    self._selector.modify(fd, mask, fd)
                except KeyError:
                    self._selector.register(fd, mask, fd)
            self._ensure_thread()
        self._wakeup()

    def remove_consumer(self, fd: int) -> None:
        with self._lock:
            self._handlers.pop(fd, None)
            try:
                self._selector.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
        self._wakeup()

    def _run(self):
        while not self._stop:
            try:
                events = self._selector.select(timeout=0.5)
            except OSError:
                continue
            # resolve the WHOLE event batch under one lock hold (a
            # deep wakeup used to pay one acquire/release per ready
            # fd), then fire callbacks outside the lock in event order
            fired = []
            with self._lock:
                for key, mask in events:
                    if key.data is None:  # wakeup pipe
                        try:
                            while self._wakeup_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                        continue
                    fd = key.data
                    h = self._handlers.get(fd)
                    if h is None:
                        continue
                    on_readable = on_writable = None
                    rearm = False
                    if mask & selectors.EVENT_READ:
                        on_readable = h[0]
                        if h[3]:              # one-shot read: disarm
                            h[2] &= ~selectors.EVENT_READ
                            rearm = True
                    if mask & selectors.EVENT_WRITE:
                        on_writable, h[1] = h[1], None  # one-shot
                        rearm = True
                    if rearm:
                        new_mask = (h[2] | (selectors.EVENT_WRITE
                                            if h[1] else 0))
                        try:
                            if new_mask:
                                self._selector.modify(fd, new_mask, fd)
                            else:
                                # keep the handler: resume_read /
                                # request_writable re-register later
                                self._selector.unregister(fd)
                                if h[0] is None:
                                    del self._handlers[fd]
                        except (KeyError, ValueError, OSError):
                            pass
                    if on_readable is not None:
                        fired.append((fd, on_readable))
                    if on_writable is not None:
                        fired.append((fd, on_writable))
            if not fired:
                continue
            self._tick_seq += 1
            self._tick_start_ns = time.monotonic_ns()
            try:
                for fd, cb in fired:
                    try:
                        cb()
                    except Exception:
                        import logging
                        logging.getLogger("brpc_tpu.transport").exception(
                            "event callback failed for fd %d", fd)
            finally:
                dur_ms = (time.monotonic_ns() - self._tick_start_ns) / 1e6
                self._tick_start_ns = 0
                if dur_ms > 1.0:
                    # sub-ms ticks are the normal case and not worth a
                    # Maxer lock; anything longer feeds the stall gauge
                    _tick_ms_max.update(dur_ms)

    def stop(self):
        self._stop = True
        self._wakeup()


_global: Optional[EventDispatcher] = None
_glock = threading.Lock()


def _new_dispatcher():
    """Lane selection, per-dispatcher: the ring lane (batched-syscall
    ticks, transport/ring_lane.py) when the event_ring_lane flag is on
    AND the native extension loads; the selector lane otherwise — and
    on ANY ring bring-up failure, so a missing compiler can never take
    eventing down with it."""
    try:
        from brpc_tpu.butil.flags import flag
        from brpc_tpu.transport import ring_lane
        if flag("event_ring_lane") and ring_lane.ring_available():
            return ring_lane.RingDispatcher()
    except Exception:
        import logging
        logging.getLogger("brpc_tpu.transport").exception(
            "ring lane unavailable; falling back to the selector lane")
    return EventDispatcher()


def global_dispatcher() -> EventDispatcher:
    global _global
    if _global is None:
        with _glock:
            if _global is None:
                _global = _new_dispatcher()
    return _global


def peek_dispatcher() -> Optional[EventDispatcher]:
    """The global dispatcher if one exists — watchdogs must observe,
    never instantiate (a fresh dispatcher has nothing to stall)."""
    return _global


def _postfork_reset() -> None:
    """Fork hygiene: the dispatcher thread exists only in the parent,
    and the inherited epoll fd is the parent's kernel object — any
    EPOLL_CTL from the child would corrupt the parent's poll set.
    Abandon the instance (closing only the child's fd copies; close(2)
    never mutates the shared interest list) so the first post-fork
    consumer builds a private dispatcher with its own thread."""
    global _global, _glock, _stall_win, _stall_win_lock
    d, _global = _global, None
    _glock = threading.Lock()
    _stall_win = None    # the Window rode the parent's sampler series
    _stall_win_lock = threading.Lock()
    if d is not None:
        d._stop = True
        abandon = getattr(d, "_postfork_abandon", None)
        if abandon is not None:    # ring lane: closes wakeups + ring
            abandon()
            return
        try:
            d._selector.close()
        except Exception:
            pass
        for s in (d._wakeup_r, d._wakeup_w):
            try:
                s.close()
            except Exception:
                pass


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("transport.event_dispatcher", _postfork_reset)
