"""In-process loopback transport (``mem://name``).

The fake fabric required by SURVEY.md §4's lesson: the whole stack must be
testable without real networking. A mem conn is a pair of byte queues with
direct readiness callbacks; it also carries device payloads by reference
(zero-copy), which is exactly what a same-host tpu:// hop degenerates to.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.transport.base import Conn, Listener, Transport

_MAX_BUFFER = 4 * 1024 * 1024  # per-direction; apply backpressure beyond


class _MemPipe:
    """One direction of a mem connection."""

    def __init__(self):
        self.lock = threading.Lock()
        self.chunks: deque = deque()
        self.size = 0
        self.closed = False
        self.device_payloads: deque = deque()


class MemConn(Conn):
    supports_device_lane = True
    # mem pipes never block the writer (bounded only by _MAX_BUFFER):
    # Socket.write may run inline in the caller's context
    inline_write_ok = True
    # read_into gathers EVERY pending chunk, so a short read proves the
    # pipe is empty — Socket._drain_readable stops without a
    # BlockingIOError round trip (and every write notifies, so nothing
    # arriving after the short read is ever missed)
    drain_all_reads = True

    def __init__(self, rx: _MemPipe, tx: _MemPipe, local: EndPoint, remote: EndPoint):
        self._rx = rx
        self._tx = tx
        self._local = local
        self._remote = remote
        self.peer: Optional["MemConn"] = None
        self._on_readable: Optional[Callable[[], None]] = None
        self._on_writable: Optional[Callable[[], None]] = None
        self._want_writable = False

    # ------------------------------------------------------------- stream
    def write(self, mv: memoryview) -> int:
        with self._tx.lock:
            if self._tx.closed:
                raise BrokenPipeError("mem conn closed")
            if self._tx.size >= _MAX_BUFFER:
                raise BlockingIOError
            data = bytes(mv)
            self._tx.chunks.append(data)
            self._tx.size += len(data)
        peer = self.peer
        if peer is not None:
            peer._notify_readable()
        return len(data)

    def read_into(self, mv: memoryview) -> int:
        with self._rx.lock:
            chunks = self._rx.chunks
            if not chunks:
                if self._rx.closed:
                    return 0
                raise BlockingIOError
            # gather every chunk that fits (drain_all_reads contract):
            # one call empties the pipe instead of one chunk per call
            n = 0
            space = len(mv)
            while chunks and n < space:
                chunk = chunks[0]
                take = min(len(chunk), space - n)
                mv[n:n + take] = chunk[:take]
                if take == len(chunk):
                    chunks.popleft()
                else:
                    chunks[0] = chunk[take:]
                n += take
            self._rx.size -= n
            was_full = self._rx.size + n >= _MAX_BUFFER > self._rx.size
        peer = self.peer
        if was_full and peer is not None:
            peer._notify_writable()
        return n

    def pending_bytes(self) -> int:
        """Unread byte count (drain_all_reads contract; GIL-atomic int
        read, no lock)."""
        return self._rx.size

    def read_chunks(self):
        """Zero-copy drain: pop every pending chunk as the exact bytes
        objects the writer enqueued (each one a complete write, usually
        one frame) — the socket wraps them as user-data blocks instead
        of copying through read_into. Returns (chunks, eof)."""
        with self._rx.lock:
            if not self._rx.chunks:
                return (), self._rx.closed
            chunks = tuple(self._rx.chunks)
            self._rx.chunks.clear()
            freed = self._rx.size
            self._rx.size = 0
            was_full = freed >= _MAX_BUFFER
        peer = self.peer
        if was_full and peer is not None:
            peer._notify_writable()
        return chunks, False

    def write_device_payload(self, arrays) -> bool:
        """Zero-copy: hand device arrays to the peer by reference."""
        with self._tx.lock:
            if self._tx.closed:
                raise BrokenPipeError("mem conn closed")
            self._tx.device_payloads.append(arrays)
        return True

    def take_device_payload(self):
        with self._rx.lock:
            if self._rx.device_payloads:
                return self._rx.device_payloads.popleft()
        return None

    def close(self) -> None:
        for pipe in (self._rx, self._tx):
            with pipe.lock:
                pipe.closed = True
        peer = self.peer
        if peer is not None:
            peer._notify_readable()  # peer reads EOF

    # ------------------------------------------------------------- events
    def start_events(self, on_readable, on_writable) -> None:
        self._on_readable = on_readable
        self._on_writable = on_writable
        with self._rx.lock:
            pending = bool(self._rx.chunks) or self._rx.closed
        if pending:
            self._notify_readable()

    def request_writable_event(self) -> None:
        with self._tx.lock:
            if self._tx.size < _MAX_BUFFER:
                fire = True
            else:
                self._want_writable = True
                fire = False
        if fire:
            self._notify_writable()

    def _notify_readable(self) -> None:
        cb = self._on_readable
        if cb is not None:
            cb()

    def _notify_writable(self) -> None:
        self._want_writable = False
        cb = self._on_writable
        if cb is not None:
            cb()

    @property
    def local_endpoint(self):
        return self._local

    @property
    def remote_endpoint(self):
        return self._remote


class _MemListener(Listener):
    def __init__(self, transport: "MemTransport", ep: EndPoint,
                 on_new_conn: Callable[[Conn], None]):
        self._transport = transport
        self._ep = ep
        self.on_new_conn = on_new_conn

    def stop(self) -> None:
        self._transport._listeners.pop(self._ep.host, None)

    @property
    def endpoint(self) -> EndPoint:
        return self._ep


class MemTransport(Transport):
    scheme = "mem"

    def __init__(self):
        self._listeners: Dict[str, _MemListener] = {}
        self._lock = threading.Lock()

    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        with self._lock:
            if ep.host in self._listeners:
                raise OSError(f"mem://{ep.host} already listening")
            lst = _MemListener(self, ep, on_new_conn)
            self._listeners[ep.host] = lst
            return lst

    def connect(self, ep: EndPoint) -> Conn:
        with self._lock:
            lst = self._listeners.get(ep.host)
        if lst is None:
            raise ConnectionRefusedError(f"no listener at mem://{ep.host}")
        a2b, b2a = _MemPipe(), _MemPipe()
        client_ep = str2endpoint(f"mem://client-{id(a2b):x}")
        client = MemConn(rx=b2a, tx=a2b, local=client_ep, remote=ep)
        server = MemConn(rx=a2b, tx=b2a, local=ep, remote=client_ep)
        client.peer = server
        server.peer = client
        lst.on_new_conn(server)
        return client
