"""ssl:// transport: TLS over the TCP lane (details/ssl_helper.cpp +
Socket's SSL state machine, src/brpc/socket.h).

The reference drives OpenSSL non-blocking: SSL_ERROR_WANT_READ/WRITE map
to the same epoll readiness dance as plain TCP. Here Python's ssl module
provides the engine; SSLWant{Read,Write}Error map to BlockingIOError (+
a writable-event request for WANT_WRITE), so Socket/KeepWrite/dispatcher
logic is untouched. The handshake runs lazily on the non-blocking
socket: reads/writes before completion drive do_handshake() instead.

Endpoint extras:
  server:  ssl://0.0.0.0:443#cert=/path/cert.pem&key=/path/key.pem
  client:  ssl://host:443            (no verification — test/dev default,
           like the reference's default ssl_options.verify.verify_depth=0)
           ssl://host:443#verify=1&ca=/path/ca.pem&sni=name
"""

from __future__ import annotations

import errno
import socket as pysocket
import ssl as pyssl
import threading
from typing import Callable, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.transport.base import Conn, Listener, Transport
from brpc_tpu.transport.event_dispatcher import global_dispatcher
from brpc_tpu.transport.tcp import TcpConn, TcpTransport


class SslConn(Conn):
    """Non-blocking TLS connection with a lazy handshake state machine
    (the reference's SSLState on Socket: SSL_CONNECTING -> SSL_CONNECTED,
    socket.h)."""

    def __init__(self, sock: pyssl.SSLSocket, local: EndPoint,
                 remote: EndPoint):
        sock.setblocking(False)
        self._sock = sock
        self._local = local
        self._remote = remote
        self._closed = False
        self._handshaken = False
        self._on_writable: Optional[Callable] = None
        # one lock around every OpenSSL call: the drain fiber and the
        # keep_write fiber otherwise race inside do_handshake()/the
        # shared SSL state machine (observed segfault); all ops are
        # non-blocking so the critical sections are short
        self._ssl_lock = threading.Lock()
        # handshake readiness routing: when the WRITE path stalls on a
        # handshake that wants a READ, arming epollout would busy-loop
        # (an established socket is always writable); instead the writer
        # parks and the read path fires its wakeup once the handshake
        # completes
        self._hs_want: Optional[str] = None
        self._writer_waiting_on_hs = False

    # ----------------------------------------------------- handshake
    def _drive_handshake(self) -> bool:
        """Advance the TLS handshake; True when established. Raises
        BlockingIOError while in progress (recording which readiness
        event would unblock it). Callers hold _ssl_lock."""
        if self._handshaken:
            return True
        try:
            self._sock.do_handshake()
        except pyssl.SSLWantReadError:
            self._hs_want = "read"
            raise BlockingIOError("tls handshake wants read")
        except pyssl.SSLWantWriteError:
            self._hs_want = "write"
            raise BlockingIOError("tls handshake wants write")
        except pyssl.SSLError as e:
            raise ConnectionError(f"tls handshake failed: {e}") from e
        self._handshaken = True
        self._hs_want = None
        return True

    def _wake_parked_writer(self) -> None:
        """Fire the writable callback for a writer that parked on a
        wants-read handshake (called with _ssl_lock NOT held)."""
        fire = False
        with self._ssl_lock:
            if self._handshaken and self._writer_waiting_on_hs:
                self._writer_waiting_on_hs = False
                fire = True
        if fire and self._on_writable is not None:
            self._on_writable()

    # ------------------------------------------------------------- io
    def write(self, mv: memoryview) -> int:
        with self._ssl_lock:
            self._drive_handshake()
            try:
                return self._sock.send(mv)
            except pyssl.SSLWantWriteError:
                raise BlockingIOError from None
            except pyssl.SSLWantReadError:
                # renegotiation wants a read; the input path will pump
                raise BlockingIOError from None
            except pyssl.SSLError as e:
                raise ConnectionError(f"tls write failed: {e}") from e
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    raise BlockingIOError from e
                raise

    def read_into(self, mv: memoryview) -> int:
        try:
            return self._read_into_locked(mv)
        finally:
            # a read may have just completed the handshake: release any
            # writer parked on it
            self._wake_parked_writer()

    def _read_into_locked(self, mv: memoryview) -> int:
        with self._ssl_lock:
            self._drive_handshake()
            try:
                return self._sock.recv_into(mv)
            except pyssl.SSLWantReadError:
                raise BlockingIOError from None
            except pyssl.SSLWantWriteError:
                self.request_writable_event()
                raise BlockingIOError from None
            except pyssl.SSLZeroReturnError:
                return 0                   # clean TLS close-notify = EOF
            except pyssl.SSLError as e:
                raise ConnectionError(f"tls read failed: {e}") from e
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    raise BlockingIOError from e
                raise

    # ------------------------------------------------------- plumbing
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        global_dispatcher().remove_consumer(self._sock.fileno())
        try:
            self._sock.close()
        except OSError:
            pass

    def start_events(self, on_readable, on_writable) -> None:
        self._on_writable = on_writable
        global_dispatcher().add_consumer(self._sock.fileno(), on_readable,
                                         oneshot_read=True)

    def resume_read_events(self) -> None:
        global_dispatcher().resume_read(self._sock.fileno())

    def request_writable_event(self) -> None:
        with self._ssl_lock:
            if not self._handshaken and self._hs_want == "read":
                # epollout on an established socket fires instantly and
                # would busy-loop for a whole handshake RTT; park the
                # writer — the read path wakes it on completion
                self._writer_waiting_on_hs = True
                return
        if self._on_writable is not None:
            global_dispatcher().request_writable(self._sock.fileno(),
                                                 self._on_writable)

    @property
    def local_endpoint(self):
        return self._local

    @property
    def remote_endpoint(self):
        return self._remote


class _SslListener(Listener):
    def __init__(self, inner: Listener, ep: EndPoint):
        self._inner = inner
        self._ep = ep

    def stop(self) -> None:
        self._inner.stop()

    @property
    def endpoint(self) -> EndPoint:
        return self._ep


class SslTransport(Transport):
    scheme = "ssl"

    def __init__(self):
        self._tcp = TcpTransport()

    # ------------------------------------------------------- contexts
    @staticmethod
    def _server_context(ep: EndPoint) -> pyssl.SSLContext:
        cert = ep.extra("cert")
        key = ep.extra("key")
        if not cert:
            raise ValueError(
                "ssl:// listener needs #cert=/path.pem (and optionally "
                "&key=/path.pem) endpoint extras")
        ctx = pyssl.SSLContext(pyssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key or None)
        return ctx

    @staticmethod
    def _client_context(ep: EndPoint) -> pyssl.SSLContext:
        verify = (ep.extra("verify") or "").lower() in ("1", "true", "yes")
        ca = ep.extra("ca")
        if verify:
            ctx = pyssl.create_default_context(
                cafile=ca if ca else None)
        else:
            ctx = pyssl.SSLContext(pyssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = pyssl.CERT_NONE
        return ctx

    # ------------------------------------------------------ transport
    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        ctx = self._server_context(ep)
        tcp_ep = EndPoint("tcp", ep.host or "127.0.0.1", ep.port, ep.extras)
        ready = threading.Event()

        def wrap(conn: TcpConn):
            if not ready.wait(5):
                conn.close()
                raise ConnectionError("ssl: listener endpoint not bound "
                                      "within 5s; dropping accepted conn")
            raw = conn._sock
            tls = ctx.wrap_socket(raw, server_side=True,
                                  do_handshake_on_connect=False)
            on_new_conn(SslConn(tls, bound, conn.remote_endpoint))

        inner = self._tcp.listen(tcp_ep, wrap)
        bound = EndPoint("ssl", inner.endpoint.host, inner.endpoint.port,
                         ep.extras)
        ready.set()
        return _SslListener(inner, bound)

    def connect(self, ep: EndPoint) -> Conn:
        ctx = self._client_context(ep)
        sni = ep.extra("sni") or ep.host
        sock = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        # blocking TCP connect, same contract as TcpTransport.connect:
        # callers (the health checker's bare-connect probe above all)
        # rely on connect() raising for an unreachable peer — a
        # swallowed non-blocking connect would revive dead servers
        sock.settimeout(10.0)
        sock.connect((ep.host, ep.port))
        sock.settimeout(None)
        lh, lp = sock.getsockname()[:2]
        tls = ctx.wrap_socket(
            sock, server_hostname=sni if ctx.check_hostname or sni else None,
            do_handshake_on_connect=False)
        try:
            tls.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        except OSError:
            pass
        return SslConn(tls, str2endpoint(f"ssl://{lh}:{lp}"), ep)
