"""tpu:// — the IN-PROCESS LOOPBACK device transport (the test fabric).

This is the fake the reference's test strategy demands (SURVEY.md §4:
everything testable over 127.0.0.1 without a cluster): host metadata
rides in-process mem pipes, device payloads hand off by reference (or a
`jax.device_put` D2D copy when src/dst ordinals differ). Both ends MUST
live in one process — there is no wire and no flow control here by
design, which also makes it the zero-overhead fixture for scheduler and
protocol tests.

The REAL device data plane is ``ici://`` (transport/ici.py): TCP
bootstrap handshake, PjRt pull-DMA lane, sliding-window + piggyback-ACK
flow control, recv-pool admission — use it for anything that crosses a
process or host boundary, and for honest performance numbers.

Endpoint form: ``tpu://name:port#device=K`` — K is the receiver's local
device ordinal.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.transport.base import Conn, Listener, Transport
from brpc_tpu.transport.mem import MemConn, _MemPipe, _MemListener


def _device_for(ordinal: Optional[int]):
    import jax

    from brpc_tpu.butil.jax_env import apply_jax_platforms_env
    apply_jax_platforms_env()   # env choice beats the plugin's override
    devs = jax.devices()
    if ordinal is None or ordinal >= len(devs):
        return devs[0]
    return devs[ordinal]


class TpuConn(MemConn):
    """Host stream = mem pipes; device lane = device_put to the peer's
    device (the PjRt Send/Recv slot)."""

    supports_device_lane = True
    lane_kind = "loopback-d2d"   # /device cell label (device_stats)

    def __init__(self, rx, tx, local, remote, peer_device_ordinal: Optional[int]):
        super().__init__(rx, tx, local, remote)
        self._peer_device_ordinal = peer_device_ordinal

    def write_device_payload(self, arrays) -> bool:
        import jax
        target = _device_for(self._peer_device_ordinal)
        moved = []
        for arr in arrays:
            if getattr(arr, "devices", None) is not None and callable(arr.devices) \
                    and target in arr.devices():
                moved.append(arr)  # already resident: zero-copy hand-off
            else:
                moved.append(jax.device_put(arr, target))
        return super().write_device_payload(moved)


class TpuTransport(Transport):
    scheme = "tpu"

    def __init__(self):
        self._listeners: Dict[str, _MemListener] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(ep: EndPoint) -> str:
        return f"{ep.host}:{ep.port}"

    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        with self._lock:
            key = self._key(ep)
            if key in self._listeners:
                raise OSError(f"tpu://{key} already listening")
            lst = _MemListener(self, ep, on_new_conn)
            self._listeners[key] = lst
            # _MemListener.stop() pops by ep.host; patch key-based removal
            lst.stop = lambda: self._listeners.pop(key, None)  # type: ignore
            return lst

    def connect(self, ep: EndPoint) -> Conn:
        with self._lock:
            lst = self._listeners.get(self._key(ep))
        if lst is None:
            raise ConnectionRefusedError(f"no listener at tpu://{self._key(ep)}")
        a2b, b2a = _MemPipe(), _MemPipe()
        server_ep = lst.endpoint
        client_ep = EndPoint("tpu", f"client-{id(a2b):x}", 0)
        # requests land on the server's device; responses land on the
        # client's reply device (the `reply_device` extra, default dev 0)
        reply = ep.extra("reply_device")
        client = TpuConn(rx=b2a, tx=a2b, local=client_ep, remote=ep,
                         peer_device_ordinal=ep.device)
        server = TpuConn(rx=a2b, tx=b2a, local=server_ep, remote=client_ep,
                         peer_device_ordinal=int(reply) if reply else None)
        client.peer = server
        server.peer = client
        lst.on_new_conn(server)
        return client
