"""Pipelined FIFO client base: the connection + batch-matching machinery
shared by protocols whose responses carry no correlation id and arrive
strictly in request order (redis RESP, memcached binary). The reference
gets this behavior from `pipelined_count` on Socket (socket.h write
options) — here it is a small base class.

Invariants:
- batch order in `_inflight` equals write order on the wire (enqueue and
  write happen under one lock; Socket.write only enqueues to the
  wait-free MPSC list, so holding the lock across it is cheap).
- batches are tied to the socket they were written on; a socket failure
  fails exactly its own batches.
- a reply timeout fails the connection: a FIFO stream cannot resync past
  a lost reply.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import create_client_socket


class Batch:
    __slots__ = ("n", "results", "event", "error", "socket")

    def __init__(self, n: int, socket=None):
        self.n = n
        self.results: List[Any] = []
        self.event = FiberEvent()
        self.error: Optional[BaseException] = None
        self.socket = socket


class PipelinedClient:
    """Subclasses set `user_data_key` (how the protocol's parse/process
    recognizes a client socket) and may override `_hello_commands()` ->
    list of wire-bytes whose replies are checked by `_check_hello_reply`.
    """

    user_data_key = "pipelined_client"

    def __init__(self, address: str | EndPoint, protocol,
                 timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address))
        self._timeout_s = timeout_s
        self._control = control or global_control()
        self._messenger = InputMessenger(protocols=[protocol],
                                         control=self._control)
        self._lock = threading.Lock()
        self._socket = None
        self._inflight: deque[Batch] = deque()

    # ---------------------------------------------------------- overrides
    def _hello_commands(self) -> List[bytes]:
        """Wire bytes to send first on a fresh connection (AUTH/SELECT...),
        one reply expected per entry."""
        return []

    def _check_hello_reply(self, reply) -> None:
        """Raise to reject the connection based on a hello reply."""

    # ------------------------------------------------------------ plumbing
    def _get_socket(self):
        with self._lock:
            s = self._socket
        if s is not None and not s.failed:
            return s
        new = create_client_socket(
            self._endpoint, on_input=self._messenger.on_new_messages,
            control=self._control)
        new.user_data[self.user_data_key] = self
        new.on_failed(self._on_socket_failed)
        hello = self._hello_commands()
        hello_batch = None
        with self._lock:
            if self._socket is not None and not self._socket.failed:
                loser, new = new, self._socket
            else:
                self._socket, loser = new, None
                if hello:
                    # first batch on the fresh connection, before any user
                    # command can enqueue
                    hello_batch = Batch(len(hello), new)
                    self._inflight.append(hello_batch)
                    buf = IOBuf()
                    for wire in hello:
                        buf.append(wire)
                    new.write(buf)
        if loser is not None:
            loser.set_failed(ConnectionError("duplicate connect discarded"))
        if hello_batch is not None:
            # surface AUTH/SELECT failure at connect time instead of
            # letting every later command fail opaquely
            if not hello_batch.event.wait_pthread(self._timeout_s):
                new.set_failed(TimeoutError("connection hello timed out"))
                raise TimeoutError("connection hello timed out")
            if hello_batch.error is not None:
                raise hello_batch.error
            for v in hello_batch.results:
                try:
                    self._check_hello_reply(v)
                except BaseException:
                    new.set_failed(ConnectionError("connection hello failed"))
                    raise
        return new

    def _on_socket_failed(self, socket):
        """Fail only the batches written on THIS socket: the loser of a
        duplicate-connect race dies with no batches, and flushing the
        winner's queue here would desync its FIFO matching."""
        failed = []
        with self._lock:
            kept = deque()
            for batch in self._inflight:
                (failed if batch.socket is socket else kept).append(batch)
            self._inflight = kept
            if self._socket is socket:
                self._socket = None
        err = getattr(socket, "fail_reason", None) or \
            ConnectionError("connection failed")
        for batch in failed:
            batch.error = err
            batch.event.set()

    def _on_reply(self, socket, value):
        with self._lock:
            if not self._inflight or self._inflight[0].socket is not socket:
                return      # stale socket's leftovers / abandoned timeout
            batch = self._inflight[0]
            batch.results.append(value)
            if len(batch.results) >= batch.n:
                self._inflight.popleft()
                done = batch
            else:
                done = None
        if done is not None:
            done.event.set()

    def _start(self, wire: bytes | IOBuf, nreplies: int) -> Batch:
        socket = self._get_socket()
        if isinstance(wire, IOBuf):
            buf = wire
        else:
            buf = IOBuf()
            buf.append(wire)
        # enqueue + write under one lock: batch order in _inflight MUST
        # match write order on the wire or FIFO matching cross-wires
        with self._lock:
            batch = Batch(nreplies, socket)
            self._inflight.append(batch)
            ok = socket.write(buf)
        if not ok:
            self._on_socket_failed(socket)
        return batch

    def _wait(self, batch: Batch, what: str = "command") -> List[Any]:
        if not batch.event.wait_pthread(self._timeout_s):
            self._fail_timeout(batch, what)
        return self._finish(batch)

    async def _wait_async(self, batch: Batch, what: str = "command") -> List[Any]:
        if not await batch.event.wait(self._timeout_s):
            self._fail_timeout(batch, what)
        return self._finish(batch)

    def _fail_timeout(self, batch: Batch, what: str):
        if batch.socket is not None:
            batch.socket.set_failed(TimeoutError(f"{what} timed out"))
        raise TimeoutError(f"{what} timed out")

    @staticmethod
    def _finish(batch: Batch) -> List[Any]:
        if batch.error is not None:
            raise batch.error
        return batch.results

    def close(self):
        with self._lock:
            s, self._socket = self._socket, None
        if s is not None and not s.failed:
            s.set_failed(ConnectionError("client closed"))
