"""Socket: THE connection object (brpc/socket.h, SURVEY.md §2.4).

Keeps the reference's load-bearing properties, re-expressed for the fiber
runtime:

- **Versioned refs**: sockets live in a global ResourcePool; a SocketId
  goes stale atomically on SetFailed (socket.cpp:776-800's _versioned_ref
  race-freedom between address() and SetFailed()).
- **Serialized wait-free-ish writes**: producers append to an MPSC queue
  and return; a single KeepWrite fiber drains it (socket.cpp:1924-2160's
  _write_head exchange + KeepWrite bthread). On EAGAIN it parks on a
  butex armed by the transport's one-shot writable event.
- **Edge-triggered input**: readiness events bump an atomic counter; only
  the 0->1 transition spawns the processing fiber (StartInputEvent's
  _nevent dance, socket.cpp:2527), which drains input until EAGAIN.
- **Device payload lane**: device arrays ride next to the byte stream on
  transports that support it (the HBM zero-copy slot where the reference
  has RDMA SGEs).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import (DEFAULT_BLOCK_SIZE, IOBuf, IOPortal,
                                  _BIG_BLOCK_SIZE)
from brpc_tpu.butil.resource_pool import INVALID_ID, ResourcePool, VersionedId
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.butex import Butex
from brpc_tpu.transport.base import Conn, get_transport

_socket_pool: ResourcePool = ResourcePool()

nwrites = Adder()
nreads = Adder()

SocketId = VersionedId


def address_socket(sid: SocketId) -> Optional["Socket"]:
    return _socket_pool.address(sid)


class Socket:
    def __init__(self, conn: Conn, on_input: Optional[Callable] = None,
                 control: Optional[TaskControl] = None):
        """``on_input(socket)`` runs in a fiber when bytes arrive
        (InputMessenger.on_new_messages in the assembled stack)."""
        self.conn = conn
        self._control = control or global_control()
        self._on_input = on_input
        self.input_portal = IOPortal()
        self.failed = False
        self.fail_reason: Optional[BaseException] = None
        self._write_q: deque = deque()           # (IOBuf, done_cb|None)
        self._write_flag_lock = threading.Lock()
        self._writing = False
        self._writable_butex = Butex(0)
        self._nevent = 0                          # edge-trigger input counter
        self._nevent_lock = threading.Lock()
        self._busy_rearmed = False   # one probe re-arm per busy period
        self._read_hint = 8192                    # adaptive read-block size
        self.preferred_protocol = -1              # InputMessenger cache
        self.user_data: dict = {}                 # per-conn session state
        # pairs a device-lane batch with its wire frame: concurrent
        # device-payload writers must not interleave (lane batches are
        # matched to messages by FIFO order)
        self.lane_lock = threading.Lock()
        self._on_failed_cbs: list = []
        self._failed_cb_lock = threading.Lock()   # failed-flag/append race
        self.id: SocketId = _socket_pool.insert(self)
        conn.start_events(self._on_readable_event, self._on_writable_event)

    # ----------------------------------------------------------- identity
    @property
    def remote_endpoint(self) -> Optional[EndPoint]:
        return self.conn.remote_endpoint

    @property
    def local_endpoint(self) -> Optional[EndPoint]:
        return self.conn.local_endpoint

    # -------------------------------------------------------------- write
    def write(self, buf: IOBuf, on_done: Optional[Callable] = None) -> bool:
        """Enqueue and return immediately; ordering is FIFO per socket.
        On an already-failed socket the done callback still fires (with the
        failure) so callers' retry paths run — never a silent drop."""
        if self.failed:
            if on_done is not None:
                try:
                    on_done(self.fail_reason)
                except Exception:
                    pass
            return False
        nwrites.add(1)
        # fast path: first write attempt in the caller's context instead
        # of bouncing through a keep_write fiber — two fiber wakeups
        # saved per RPC roundtrip. Opt-in invariant (inline_write_ok):
        # the conn's write() raises BlockingIOError on EAGAIN (which
        # cut_into_writer absorbs, leaving the remainder in `buf`), so
        # a partial/blocked write lands in the handoff branch below —
        # never in the except arm. mem/tpu pipes never block; TCP relies
        # on the handoff. The _writing flag is claimed exactly like
        # keep_write does, so FIFO order holds against concurrent
        # writers (losers enqueue; we drain them after).
        if getattr(self.conn, "inline_write_ok", False):
            with self._write_flag_lock:
                fast = not self._writing and not self._write_q
                if fast:
                    self._writing = True
            if fast:
                err: Optional[BaseException] = None
                try:
                    buf.cut_into_writer(self.conn.write)
                except (BrokenPipeError, ConnectionError, OSError) as e:
                    err = e
                if err is None and not buf:
                    with self._write_flag_lock:
                        self._writing = False
                        more = bool(self._write_q)
                    if on_done is not None:
                        try:
                            on_done(None)
                        except Exception:
                            pass
                    if more:
                        self._maybe_start_keep_write()
                    return True
                # leftover or error: hand off to the slow path with the
                # flag still held — _keep_write owns it from here
                self._write_q.appendleft((buf, on_done))
                if err is not None:
                    self.set_failed(err)
                self._control.spawn(self._keep_write, name="keep_write")
                return err is None
        self._write_q.append((buf, on_done))
        self._maybe_start_keep_write()
        return True

    def write_device_payload(self, arrays) -> bool:
        """Out-of-band device lane (mem/tpu transports); host transports
        must serialize instead."""
        r = self.conn.write_device_payload(arrays)
        return bool(r)

    def _maybe_start_keep_write(self):
        with self._write_flag_lock:
            if self._writing or not self._write_q:
                return
            self._writing = True
        self._control.spawn(self._keep_write, name="keep_write")

    async def _keep_write(self):
        while True:
            try:
                item = self._write_q.popleft()
            except IndexError:
                item = None
            if item is None:
                with self._write_flag_lock:
                    if not self._write_q:
                        self._writing = False
                        return
                continue
            buf, on_done = item
            err: Optional[BaseException] = None
            while buf and not self.failed:
                try:
                    buf.cut_into_writer(self.conn.write)
                except (BrokenPipeError, ConnectionError, OSError) as e:
                    err = e
                    break
                if buf:
                    # blocked: arm one-shot writable event, park on butex
                    seq = self._writable_butex.value
                    self.conn.request_writable_event()
                    await self._writable_butex.wait(expected=seq, timeout_s=1.0)
            if err is None and buf and self.failed:
                err = self.fail_reason  # failed mid-write: not a success
            if err is not None:
                self.set_failed(err)
            if on_done is not None:
                try:
                    on_done(err)
                except Exception:
                    pass
            if self.failed:
                # drain remaining writes with failure callbacks
                while True:
                    try:
                        _, cb = self._write_q.popleft()
                    except IndexError:
                        break
                    if cb is not None:
                        try:
                            cb(self.fail_reason)
                        except Exception:
                            pass
                with self._write_flag_lock:
                    self._writing = False
                return

    def _on_writable_event(self):
        self._writable_butex.fetch_add(1)
        self._writable_butex.wake_all()

    # -------------------------------------------------------------- input
    def _on_readable_event(self):
        """May fire from the dispatcher thread or a peer's fiber; only the
        0->1 transition starts a processing fiber."""
        with self._nevent_lock:
            self._nevent += 1
            if self._nevent > 1:
                busy = True
            else:
                busy = False
        if not busy:
            self._control.spawn(self._process_input, name="socket_input")
            return
        # the input fiber is busy — possibly SUSPENDED awaiting a long
        # handler, in which case it cannot drain this event for a
        # while. A dead peer must still become visible NOW
        # (Controller::IsCanceled / NotifyOnCancel): cheap non-consuming
        # EOF probe from the dispatcher (the reference's event
        # dispatcher detects the hangup independently of message
        # processing for the same reason)
        peek = getattr(self.conn, "peek_closed", None)
        if peek is not None:
            try:
                if peek():
                    # NOT inline: set_failed runs user notify_on_cancel
                    # callbacks — a blocking one must not stall the
                    # process-wide dispatcher thread (the reference runs
                    # NotifyOnCancel in a fresh bthread)
                    self._control.spawn(
                        lambda: self.set_failed(
                            ConnectionResetError("peer closed")))
                elif not self._busy_rearmed:
                    # data (not FIN) arrived while the input fiber is
                    # busy: with one-shot arming this event consumed the
                    # read interest — re-arm so a later FIN during the
                    # same handler still produces an event. ONCE per
                    # busy period (flag cleared when the input fiber
                    # drains to idle): unconditional re-arm with data
                    # pending would storm the dispatcher (event -> peek
                    # -> re-arm -> immediate event ...), and the input
                    # loop re-drains pending data anyway via _nevent
                    self._busy_rearmed = True
                    resume = getattr(self.conn, "resume_read_events", None)
                    if resume is not None:
                        resume()
            except Exception:
                pass

    async def _process_input(self):
        while True:
            with self._nevent_lock:
                pending = self._nevent
            progressed = self._drain_readable()
            if self._on_input is not None and (self.input_portal or self.failed):
                try:
                    r = self._on_input(self)
                    if hasattr(r, "__await__"):
                        await r
                except BaseException as e:
                    # an escaping parse/process error must not wedge the
                    # socket (the fiber dying would leave _nevent elevated
                    # and no future event would respawn us): drop the conn
                    import logging
                    logging.getLogger("brpc_tpu.transport").exception(
                        "input processing failed; dropping connection")
                    self.set_failed(e if isinstance(e, Exception)
                                    else ConnectionError(str(e)))
            with self._nevent_lock:
                self._nevent -= pending
                if self._nevent > 0:
                    continue
                self._busy_rearmed = False   # busy period over
                return

    def _drain_readable(self) -> int:
        """Read until EAGAIN/EOF into the portal; returns bytes read.

        Read blocks are sized adaptively: full reads grow the next
        block (up to 256KB) so bulk transfers take few recv syscalls,
        small reads shrink it back so idle connections don't hold large
        buffers — the readv-into-many-blocks effect of
        iobuf.h:469 without the iovec."""
        total = 0
        while not self.failed:
            hint = self._read_hint
            try:
                n = self.input_portal.append_from_reader(
                    self.conn.read_into, hint=hint)
            except BlockingIOError:
                # drained: with one-shot read arming, the dispatcher
                # won't fire again until we re-arm
                resume = getattr(self.conn, "resume_read_events", None)
                if resume is not None:
                    resume()
                break
            except (ConnectionError, OSError) as e:
                self.set_failed(e)
                break
            if n == 0:  # EOF
                self.set_failed(ConnectionResetError("peer closed"))
                break
            if n >= hint:
                # jump straight to the big recyclable size: intermediate
                # sizes would allocate non-poolable buffers
                self._read_hint = _BIG_BLOCK_SIZE
            elif n < 4096:
                self._read_hint = DEFAULT_BLOCK_SIZE
            total += n
            nreads.add(n)
        return total

    def take_device_payload(self):
        take = getattr(self.conn, "take_device_payload", None)
        return take() if take is not None else None

    # ------------------------------------------------------------ failure
    def set_failed(self, reason: Optional[BaseException] = None) -> None:
        """Version-bump the id (outstanding SocketIds go stale), close the
        conn, fire failure callbacks (SetFailed, socket.cpp)."""
        with self._failed_cb_lock:
            if self.failed:
                return
            self.failed = True
            self.fail_reason = reason or ConnectionError("socket set_failed")
            cbs = list(self._on_failed_cbs)
        _socket_pool.remove(self.id)
        try:
            self.conn.close()
        except Exception:
            pass
        self._writable_butex.fetch_add(1)
        self._writable_butex.wake_all()
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass

    def on_failed(self, cb: Callable[["Socket"], None]) -> None:
        # flag-check and append under one lock: a registration racing
        # set_failed's snapshot would otherwise be lost forever
        # (notify_on_cancel waiters would never fire)
        with self._failed_cb_lock:
            if not self.failed:
                self._on_failed_cbs.append(cb)
                return
        cb(self)

    def off_failed(self, cb: Callable[["Socket"], None]) -> None:
        """Unsubscribe a failure callback (no-op if absent): long-lived
        multiplexed sockets must not accumulate dead subscribers."""
        with self._failed_cb_lock:
            try:
                self._on_failed_cbs.remove(cb)
            except ValueError:
                pass


def create_client_socket(ep: EndPoint, on_input: Optional[Callable] = None,
                         control: Optional[TaskControl] = None) -> Socket:
    conn = get_transport(ep.scheme).connect(ep)
    return Socket(conn, on_input=on_input, control=control)
