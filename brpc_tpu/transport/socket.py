"""Socket: THE connection object (brpc/socket.h, SURVEY.md §2.4).

Keeps the reference's load-bearing properties, re-expressed for the fiber
runtime:

- **Versioned refs**: sockets live in a global ResourcePool; a SocketId
  goes stale atomically on SetFailed (socket.cpp:776-800's _versioned_ref
  race-freedom between address() and SetFailed()).
- **Serialized wait-free-ish writes**: producers append to an MPSC queue
  and return; a single KeepWrite fiber drains it (socket.cpp:1924-2160's
  _write_head exchange + KeepWrite bthread). On EAGAIN it parks on a
  butex armed by the transport's one-shot writable event.
- **Edge-triggered input**: readiness events bump an atomic counter; only
  the 0->1 transition spawns the processing fiber (StartInputEvent's
  _nevent dance, socket.cpp:2527), which drains input until EAGAIN.
- **Device payload lane**: device arrays ride next to the byte stream on
  transports that support it (the HBM zero-copy slot where the reference
  has RDMA SGEs).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.butil.iobuf import (DEFAULT_BLOCK_SIZE, IOBuf, IOPortal,
                                  _BIG_BLOCK_SIZE)
from brpc_tpu.butil.resource_pool import INVALID_ID, ResourcePool, VersionedId
from brpc_tpu.bvar.reducer import Adder, Maxer, PassiveStatus
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.butex import Butex
from brpc_tpu.transport.base import Conn, get_transport
from brpc_tpu.transport import device_stats as _device_stats
from brpc_tpu.transport import syscall_stats as _syscall_stats
from brpc_tpu.transport.ring_lane import try_defer_write as _try_defer_write

define_flag("socket_inline_process", True,
            "process socket input inline on the event-raising thread "
            "until the handler first suspends (process-in-place, "
            "input_messenger.cpp:183); handlers that await park as "
            "normal fibers. Off = always spawn a fiber per busy period")

# Writes at/above this size claim writership through a keep_write fiber
# instead of sending inline from the submitting context: the kernel
# copy of a large frame (a sendmsg releases the GIL for its whole
# duration) then overlaps with whatever the submitter does next — on
# the event thread that means the NEXT frame's recv runs concurrently
# with this frame's send, which is the difference between one thread
# and two threads carrying the 1MB echo pipeline. 0 disables (single-
# core hosts: there is nothing to overlap with, and the fiber wake is
# pure cost). Applies only to fd transports (kernel-copy writes).
define_flag("socket_async_write_min",
            131072 if (os.cpu_count() or 1) > 1 else 0,
            "min frame bytes routed to a keep_write fiber instead of "
            "the inline send (0 = always inline); fd transports only")

# gather-write coalescing bounds: adjacent queued frames merge into one
# writev/sendmsg batch up to these caps (the iovec cap keeps a batch
# under IOV_MAX with headroom; the byte cap bounds how much one syscall
# pins while the queue drains)
_COALESCE_MAX_FRAMES = 32
_COALESCE_MAX_BYTES = 1 << 20


def _composite_cb(pending_cbs):
    """One done-callback firing a batch's unfired per-frame callbacks —
    the parked-remainder composite every write lane hands to
    _park_handoff. None when there is nothing to fire."""
    if not pending_cbs:
        return None

    def comp(err, _cbs=pending_cbs):
        for c in _cbs:
            try:
                c(err)
            except Exception:
                pass
    return comp


def _close_pinned(cell) -> None:
    """Finalizer for a socket's pinned-fd cell (belt and braces: the
    normal close runs at set_failed once no native loop holds it)."""
    fd, cell[0] = cell[0], -1
    if fd is not None and fd >= 0:
        try:
            os.close(fd)
        except OSError:
            pass


class _PyMpsc:
    """Fallback for fastcore's Mpsc (queues.cc writer-retire MPSC) with
    the same contract: push() returns True when the caller became the
    writer; the writer drains FIFO and releases via try_retire(), which
    refuses while items remain (socket.cpp StartWrite/IsWriteComplete)."""

    __slots__ = ("_q", "_lock", "_writing")

    def __init__(self):
        self._q = deque()
        self._lock = threading.Lock()
        self._writing = False

    def push(self, item) -> bool:
        with self._lock:
            self._q.append(item)
            if self._writing:
                return False
            self._writing = True
            return True

    def drain_one(self):
        with self._lock:
            return self._q.popleft() if self._q else None

    def try_retire(self) -> bool:
        with self._lock:
            if self._q:
                return False
            self._writing = False
            return True

    def depth(self) -> int:
        return len(self._q)




# socket versioned-ref pool (socket.cpp:776-800): native respool.cc
# slots when available. Resolved on first use — fastcore.get() may
# compile the extension, and import must stay cheap.
_socket_pool = None
_socket_pool_lock = threading.Lock()


def _pool():
    p = _socket_pool
    if p is None:
        p = _make_pool()
    return p


def _make_pool():
    # locked: concurrent first-socket threads must agree on ONE pool
    # (a Socket registered in a discarded duplicate would be
    # unaddressable and set_failed would remove from the wrong pool)
    global _socket_pool
    with _socket_pool_lock:
        if _socket_pool is None:
            from brpc_tpu.native import fastcore as _fastcore
            fc = _fastcore.get()
            _socket_pool = fc.Pool(1 << 16) if fc is not None \
                else ResourcePool()
        return _socket_pool


def _new_mpsc():
    from brpc_tpu.native import fastcore as _fastcore
    fc = _fastcore.get()
    return fc.Mpsc() if fc is not None else _PyMpsc()


# fastcore module for the per-call fd loops (pluck_scan); resolved on
# first use for the same import-cost reason as the pools above
_fc_mod = False


def _fastcore():
    global _fc_mod
    if _fc_mod is False:
        from brpc_tpu.native import fastcore as _fastcore_loader
        _fc_mod = _fastcore_loader.get()
    return _fc_mod

# socket-level traffic + fast-lane health, visible at /vars (the
# reference self-instruments every subsystem the same way)
nwrites = Adder().expose("socket_writes")
nreads = Adder().expose("socket_read_bytes")
npluck_fast = Adder().expose("pluck_fast_responses")   # native-loop wins
npluck_defer = Adder().expose("pluck_defers")          # classic fallbacks
# write-queue saturation: bytes accepted by write() but not yet handed
# to the conn, across all sockets (a live gauge: +size at enqueue,
# -size at dequeue) — sustained growth means peers or the network can't
# absorb the response rate, which an rpcz timeline shows as write_us.
# The windowed peak catches bursts a point sample between drains misses.
nwqueue_bytes = Adder().expose("socket_wqueue_bytes")
_wqueue_peak = Maxer()
# frames that left in a merged gather-write batch beyond the first —
# each one is a send/sendmsg syscall the coalescer removed
ncoalesced = Adder().expose("socket_write_coalesced_frames")

# ---------------------------------------------------------------- census
# Every live Socket, regardless of owner (server conns, client channel
# sockets): the resource census measures per-connection cost across the
# whole process, not just one server's accept list. WeakSet so the
# registry itself can never pin a connection's memory. The lock
# serializes ADDs against census snapshots (a concurrent add during
# iteration raises "Set changed size"; GC-driven removals are already
# deferred by WeakSet's own _IterationGuard).
_live_sockets: "weakref.WeakSet" = weakref.WeakSet()
_live_sockets_lock = threading.Lock()

define_flag("census_idle_s", 10.0,
            "a connection with no read/write activity for this long "
            "counts as idle on /census, /connections and the "
            "idle_conn_count bvar")


_rows_memo = (0.0, [])     # (expires_monotonic, rows) — GIL-atomic swap


def socket_census_rows(max_age_s: float = 0.2):
    """One pass over every live, non-failed socket: (socket, resident
    bytes, idle seconds). THE shared accounting authority — the /census
    subsystem totals, the /connections per-conn rows and the idle/avg
    bvars all derive from this, so they cannot disagree on what a
    connection 'costs'. Resident bytes = parser-buffered input + queued
    unsent output (the two elastic per-conn buffers; fixed object
    overhead is what bytes_per_idle_conn measures via RSS).

    Memoized for ``max_age_s`` (0 forces fresh): one /vars scrape
    evaluates BOTH census gauges and a shard dump adds the census
    provider — without the memo that is three full walks over every
    live connection per scrape, which matters at the 100k-conn
    target."""
    global _rows_memo
    now_mono = time.monotonic()
    expires, rows = _rows_memo
    if max_age_s > 0 and now_mono < expires:
        return rows
    now = time.monotonic_ns()
    with _live_sockets_lock:
        socks = list(_live_sockets)
    rows = []
    for s in socks:
        if s is None or s.failed:
            continue
        rows.append((s, s.input_portal.size + s.wq_bytes,
                     (now - s.last_active_ns) / 1e9))
    _rows_memo = (now_mono + 0.2, rows)
    return rows


def _socket_census() -> dict:
    """Process-wide socket census, with the server-side subset broken
    out: ``bytes``/``count`` cover EVERY live socket (client channels
    included — they cost memory too), while ``server_bytes``/
    ``server_count`` cover only accepted server connections, the set
    /connections lists (a server conn carries user_data['server'])."""
    rows = socket_census_rows()
    idle_after = flag("census_idle_s")
    srv = [(s, b, i) for s, b, i in rows
           if s.user_data.get("server") is not None]
    return {
        "bytes": sum(b for _, b, _ in rows),
        "count": len(rows),
        "idle": sum(1 for _, _, i in rows if i >= idle_after),
        "server_bytes": sum(b for _, b, _ in srv),
        "server_count": len(srv),
    }


def idle_conn_count() -> int:
    idle_after = flag("census_idle_s")
    return sum(1 for _, _, i in socket_census_rows() if i >= idle_after)


def conn_resident_bytes_avg() -> float:
    rows = socket_census_rows()
    if not rows:
        return 0.0
    return round(sum(b for _, b, _ in rows) / len(rows), 1)


def expose_conn_census_vars() -> None:
    """(Re-)expose the connection-cost bvars — called at import and
    again from Server.start, surviving a test fixture's unexpose_all
    like the other socket counters."""
    _idle_var.expose("idle_conn_count")
    _avg_var.expose("conn_resident_bytes_avg")


_idle_var = PassiveStatus(idle_conn_count)
_avg_var = PassiveStatus(conn_resident_bytes_avg)
expose_conn_census_vars()

from brpc_tpu.butil import resource_census as _resource_census  # noqa: E402
#   (census registration ships with the socket registry it measures)

_resource_census.register("sockets", _socket_census)


def _wqueue_peak_window():
    """Windowed high-water mark of any single socket's queued bytes,
    created lazily (a Window starts the background sampler thread).
    Locked double-check: a losing racer's Window would stay registered
    with the sampler and drain the delta-mode Maxer via reset() each
    tick, zeroing the kept window's samples."""
    global _wq_peak_win
    if _wq_peak_win is None:
        with _wq_peak_win_lock:
            if _wq_peak_win is None:
                from brpc_tpu.bvar.window import Window
                _wq_peak_win = Window(_wqueue_peak, 10)
    return _wq_peak_win


_wq_peak_win = None
_wq_peak_win_lock = threading.Lock()


def _postfork_reset() -> None:
    """Fork hygiene: the versioned-ref socket pool addresses PARENT
    sockets (their fds are mere dup'd copies here, their event
    registrations live in the parent's dispatcher), and the peak
    window rides the parent's sampler. Fresh child, fresh pool."""
    global _socket_pool, _socket_pool_lock, _wq_peak_win
    global _live_sockets_lock, _rows_memo, _wq_peak_win_lock
    _socket_pool = None
    _socket_pool_lock = threading.Lock()
    _wq_peak_win = None
    _wq_peak_win_lock = threading.Lock()
    _rows_memo = (0.0, [])    # memoized rows describe parent sockets
    # census registry: the listed sockets are the PARENT's connections
    # (the child holds mere fd dups it will never serve), and the lock
    # may have been mid-hold at fork time
    _live_sockets_lock = threading.Lock()
    _live_sockets.clear()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singletons it resets)

_postfork.register("transport.socket", _postfork_reset)

# Installed by the RPC layer (brpc_tpu.rpc.channel): callable
# ``(socket, [controllers])`` that fails or re-issues the client calls
# still in flight on a socket that just failed — the transport layer
# defines the hook, the RPC layer provides the semantics (the
# reference's SetFailed -> bthread_id_error fan-out, socket.cpp).
inflight_failer = None


def pull_chunks(sock):
    """Shared front half of the chunk-handoff fast lanes (mem://): pull
    the writer's exact bytes objects off the conn, with the common
    eligibility/eof/accounting protocol in ONE place so the client and
    server lanes cannot diverge on it. Returns (data, handled):
    data=None means no scanning to do — handled tells the hook what to
    return (True: spurious wake or eof dealt with; False: not a chunk
    conn, and the hook was self-disabled)."""
    rc = getattr(sock.conn, "read_chunks", None)
    if rc is None:
        sock.fast_drain = None
        return None, False
    chunks, eof = rc()
    if eof:
        # the classic chunk drain's verdict (Socket._drain_readable)
        sock.set_failed(ConnectionResetError("peer closed"))
        return None, True
    if not chunks:
        return None, True
    data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
    nreads.add(len(data))
    return data, False

SocketId = VersionedId


def address_socket(sid: SocketId) -> Optional["Socket"]:
    return _pool().address(sid)


class Socket:
    def __init__(self, conn: Conn, on_input: Optional[Callable] = None,
                 control: Optional[TaskControl] = None):
        """``on_input(socket)`` runs in a fiber when bytes arrive
        (InputMessenger.on_new_messages in the assembled stack)."""
        self.conn = conn
        self._control = control or global_control()
        self._on_input = on_input
        # sync twin of the input callback (InputMessenger's
        # on_new_messages_sync): lets the whole drain+parse+dispatch
        # cycle run without coroutine/fiber machinery when nothing
        # suspends — the client response path in particular
        self._on_input_sync = None
        if on_input is not None and \
                getattr(on_input, "__name__", "") == "on_new_messages":
            self._on_input_sync = getattr(
                getattr(on_input, "__self__", None),
                "on_new_messages_sync", None)
        self.input_portal = IOPortal()
        self.failed = False
        self.fail_reason: Optional[BaseException] = None
        # wait-free MPSC write queue with writer-retire arbitration
        # (native queues.cc via fastcore when available): items are
        # (bytes|IOBuf, done_cb|None); the producer whose push claims
        # writership drains — socket.cpp:1924-2005's _write_head protocol
        self._wq = _new_mpsc()
        # mid-frame leftover of a parked writer: (IOBuf, cb). INVARIANT:
        # non-None exactly while writership is parked awaiting a
        # writable event; consuming it (under _handoff_lock) IS taking
        # writership. Both the writable-event continuation and
        # set_failed's cleanup race for it — exactly one wins.
        self._handoff = None
        self._handoff_lock = threading.Lock()
        self._writable_butex = Butex(0)
        self._nevent = 0                          # edge-trigger input counter
        self._nevent_lock = threading.Lock()
        self._plucking = False       # a sync joiner owns input processing
        # dispatched requests whose response hasn't been written yet —
        # the cut-through gate: streaming a response in pieces is only
        # frame-safe while no other response can interleave
        self.pending_responses = 0
        self.pending_lock = threading.Lock()
        # client-side calls currently issued on this socket (balanced by
        # Controller._set_issue_socket) — the sync-pluck lazy-deadline
        # gate: with >1 in flight, another call's big response could
        # stall a plucker past its deadline, so those joiners keep the
        # real timer; _lazy_plucker is the controller currently plucking
        # WITH a lazy deadline, armed by a later issuer (both under
        # pending_lock)
        self.client_inflight = 0
        self.inflight_calls: set = set()   # their controllers (same lock)
        self._lazy_plucker = None
        self._busy_rearmed = False   # one probe re-arm per busy period
        self._busy_paused = False    # level-trigger: read interest paused
        # sticky pluck pause: after a sync-pluck settles with nothing in
        # flight, read interest STAYS paused (the next pluck_preclaim
        # consumes it for free — per-call epoll_ctl pair removed from
        # the sync-RPC path). Any non-pluck consumer of the socket
        # (async issue registration, a direct write, a stream binding)
        # must unstick first; _submit does. _sticky_since gates the
        # dead-peer probe (probe_unobserved) to genuinely idle reuse.
        self._pluck_sticky = False
        self._sticky_since = 0.0
        self._read_hint = 8192                    # adaptive read-block size
        self.preferred_protocol = -1              # InputMessenger cache
        # protocol hint: total portal bytes needed before the next parse
        # can succeed (a 1MB frame arrives in ~5 drain cycles; without
        # this each cycle re-probes header/meta just to learn "not yet")
        self.input_need = 0
        # server native drain hook (fastcore serve_drain): a callable
        # ``(socket) -> bool`` tried before the classic drain on the
        # sync input path; True = the pass was handled natively.
        # Installed by Server for eligible sockets, self-disabling.
        self.fast_drain: Optional[Callable] = None
        # ring lane (transport/ring_lane.py): bytes the dispatcher tick
        # recv'd natively queue here under _nevent_lock; the OWNING
        # processing context moves them into the portal
        # (_drain_readable's ring branch), so appender and parser never
        # touch the portal concurrently — the classic lane's
        # single-consumer invariant, kept structurally. _ring_fed marks
        # a pass whose bytes arrived this way (initialized BEFORE
        # start_events: a ring completion can fire mid-__init__).
        self._ring_chunks: list = []
        self._ring_fed = False
        self._ring_attached = False
        self._ring_pluck_ok = True
        self.user_data: dict = {}                 # per-conn session state
        # last read-event/write stamp (monotonic ns): the idle-class
        # signal for /census, /connections and idle_conn_count — one
        # attr store per readable event / queued write
        self.last_active_ns = time.monotonic_ns()
        # bytes enqueued to _wq and not yet popped by a writer (owner
        # thread +=, writer -=; GIL-atomic enough for a gauge) — the
        # per-socket write-queue saturation signal (/sockets page)
        self.wq_bytes = 0
        # pairs a device-lane batch with its wire frame: concurrent
        # device-payload writers must not interleave (lane batches are
        # matched to messages by FIFO order)
        self.lane_lock = threading.Lock()
        self._on_failed_cbs: list = []
        self._failed_cb_lock = threading.Lock()   # failed-flag/append race
        # captured once: /flags mutation applies to new sockets (a dict
        # lookup per readable event is measurable on the inline path)
        self._inline_process = flag("socket_inline_process")
        self._inline_write = getattr(conn, "inline_write_ok", False)
        self._drain_all_reads = getattr(conn, "drain_all_reads", False)
        self._level_triggered = getattr(conn, "level_triggered", False)
        self._writev = getattr(conn, "writev", None)
        self._readv = getattr(conn, "read_into_v", None)
        self._read_chunks = getattr(conn, "read_chunks", None)
        # async big-write routing applies only to kernel-copy fd conns
        # (pluck_fd is the "real fd" marker shared with the pluck lane)
        self._async_write_min = (flag("socket_async_write_min")
                                 if getattr(conn, "pluck_fd", None)
                                 is not None else 0)
        # pinned-fd cache for the native fd loops (pluck_scan /
        # serve_drain): ONE dup per socket instead of one dup+close
        # per call/event. Refcounted so set_failed can close it the
        # moment no native loop holds it (a lingering dup would delay
        # the FIN a set_failed close is supposed to send).
        self._pin_lock = threading.Lock()
        self._pin_cell = [None]      # dup'd fd (None = not yet, -1 = closed)
        self._pin_refs = 0
        self._pin_closed = False
        weakref.finalize(self, _close_pinned, self._pin_cell)
        try:
            self.id: SocketId = _pool().insert(self)
        except RuntimeError:
            # bounded native pool (65536 live sockets): surface as a
            # connection error the RPC paths already handle — and close
            # the conn NOW (start_events never runs, so nothing else
            # will), or every rejected connect leaks an fd exactly when
            # the process is resource-exhausted
            try:
                conn.close()
            except Exception:
                pass
            raise ConnectionError("socket pool exhausted") from None
        with _live_sockets_lock:         # resource-census registry
            _live_sockets.add(self)
        # ring lane: offer the completion sink BEFORE start_events —
        # the conn decides there (ring-mode dispatcher + plain fd)
        # whether to register ring-native or classic
        if getattr(conn, "supports_ring_sink", False):
            conn.ring_sink = self.ring_input
        conn.start_events(self._on_readable_event, self._on_writable_event)
        self._ring_attached = getattr(conn, "ring_attached", False)
        self._ring_pluck_ok = getattr(conn, "ring_pluck_ok", True)

    # ---------------------------------------------------------- pinned fd
    def pin_fd_acquire(self) -> int:
        """Acquire the cached dup of the conn's fd for a native loop
        (pluck_scan / serve_drain). The dup pins the kernel socket: a
        concurrent set_failed closes the conn's own fd while the C
        loop sits in poll/recv with the GIL released, and the OS could
        hand that fd NUMBER to a brand-new connection whose bytes the
        loop would then consume. Returns -1 when unavailable (no fd
        conn, already closed, dup failed). MUST be balanced by
        pin_fd_release()."""
        with self._pin_lock:
            if self._pin_closed:
                return -1
            fd = self._pin_cell[0]
            if fd is None:
                pfd = getattr(self.conn, "pluck_fd", None)
                if pfd is None:
                    return -1
                try:
                    fd = os.dup(pfd())
                except OSError:
                    return -1
                self._pin_cell[0] = fd
            self._pin_refs += 1
            return fd

    def pin_fd_release(self) -> None:
        with self._pin_lock:
            self._pin_refs -= 1
            if (self._pin_refs == 0 and self._pin_closed
                    and self._pin_cell[0] is not None
                    and self._pin_cell[0] >= 0):
                fd, self._pin_cell[0] = self._pin_cell[0], -1
                try:
                    os.close(fd)
                except OSError:
                    pass

    def _pin_fd_shutdown(self) -> None:
        """set_failed's half: close the pinned dup as soon as no native
        loop holds it (the loop in flight sees EOF/reset through its
        still-open dup and releases; the LAST releaser closes)."""
        with self._pin_lock:
            self._pin_closed = True
            if (self._pin_refs == 0 and self._pin_cell[0] is not None
                    and self._pin_cell[0] >= 0):
                fd, self._pin_cell[0] = self._pin_cell[0], -1
                try:
                    os.close(fd)
                except OSError:
                    pass

    # ----------------------------------------------------------- identity
    @property
    def remote_endpoint(self) -> Optional[EndPoint]:
        return self.conn.remote_endpoint

    @property
    def local_endpoint(self) -> Optional[EndPoint]:
        return self.conn.local_endpoint

    # -------------------------------------------------------------- write
    def write(self, data, on_done: Optional[Callable] = None) -> bool:
        """Enqueue an IOBuf or a ready-made bytes frame and return
        immediately; ordering is FIFO per socket. Bytes frames skip the
        IOBuf machinery unless the conn blocks mid-frame (the reference's
        write-once-in-place, socket.cpp:1960). On an already-failed
        socket the done callback still fires (with the failure) so
        callers' retry paths run — never a silent drop."""
        return self._submit(data, on_done)

    # bytes and IOBufs share one path; the old two-name split survives as
    # an alias so fast-path call sites read as what they are
    write_small = write

    def _submit(self, data, on_done) -> bool:
        """One write path for bytes and IOBufs: push onto the MPSC queue;
        the producer whose push CLAIMS writership sends — inline in this
        context when the conn allows it (write-once-then-KeepWrite,
        socket.cpp:1924-2050), via a keep_write fiber otherwise. FIFO
        holds because the queue is the only ordering authority."""
        if self.failed:
            if on_done is not None:
                try:
                    on_done(self.fail_reason)
                except Exception:
                    pass
            return False
        if self._pluck_sticky and not self._plucking:
            # a non-pluck writer is using a sticky-paused socket: the
            # response/peer data needs live read events again
            self.unstick_reads()
        nwrites.add(1)
        self.last_active_ns = time.monotonic_ns()
        sz = data.size if isinstance(data, IOBuf) else len(data)
        # graftlint: disable=guarded-by -- wq_bytes is approximate
        # accounting beside the wait-free write queue: a lock here
        # would sit on every submit of every thread, and drift only
        # skews an observability gauge, never the queue itself.
        self.wq_bytes += sz
        nwqueue_bytes.add(sz)
        _wqueue_peak.update(self.wq_bytes)
        if not self._wq.push((data, on_done)):
            return True          # the active writer drains it in order
        if self._ring_attached and type(data) is bytes and \
                _try_defer_write(self):
            # mid-tick on the ring thread: writership just claimed by
            # the push is handed to the tick's end-of-batch flush — the
            # whole burst's responses leave as one gather writev per
            # connection instead of one send per frame
            return True
        m = self._async_write_min
        if self._inline_write and not (m and sz >= m):
            return self._drain_writes_inline()
        self._control.spawn(self._keep_write, name="keep_write")
        return True

    def _wq_acct_pop(self, item) -> None:
        """Settle the write-queue gauge for one popped item (called at
        drain_one sites only — a handoff continuation was already
        settled when the item first left the queue)."""
        data = item[0]
        sz = data.size if isinstance(data, IOBuf) else len(data)
        self.wq_bytes -= sz
        nwqueue_bytes.add(-sz)

    def _write_data_once(self, data):
        """Single pass over one item; returns (err, leftover_iobuf|None).
        BlockingIOError is absorbed into a leftover (never an error)."""
        try:
            if isinstance(data, IOBuf):
                self._cut_buf(data)
                return None, (data if data else None)
            # whole-frame send first: the common small frame leaves in
            # one syscall with no memoryview/loop machinery
            try:
                n = self.conn.write(data) or 0
            except BlockingIOError:
                # fully blocked: don't pay a second guaranteed-EAGAIN
                # send — park the whole frame
                buf = IOBuf()
                buf.append(bytes(data))
                return None, buf
            if n == len(data):
                return None, None
            mv = memoryview(data)[n:]
            while mv:
                try:
                    n = self.conn.write(mv)
                except BlockingIOError:
                    break
                if n is None or n <= 0:
                    break
                mv = mv[n:]
            if mv:
                buf = IOBuf()
                buf.append(bytes(mv))
                return None, buf
            return None, None
        except (BrokenPipeError, ConnectionError, OSError) as e:
            return e, None

    def _drain_writes_inline(self, first_item=None) -> bool:
        """Writer loop in the claiming context (push claim, a writable-
        event continuation, or set_failed's cleanup). On EAGAIN the
        partial frame parks in _handoff with writership attached and a
        one-shot writable event re-enters this loop ON THE DISPATCHER —
        no fiber, no worker wake per blocked write (the reference pays a
        bthread park/wake here, which is ~1us for it and ~50us for us)."""
        ok = True
        item = first_item
        while True:
            if item is None:
                item = self._wq.drain_one()
                if item is not None:
                    self._wq_acct_pop(item)
            if item is None:
                if self._wq.try_retire():
                    return ok
                continue          # a racing push landed: keep draining
            data, cb = item
            item = None
            err: Optional[BaseException] = None
            if not self.failed and self._writev is not None:
                # gather-write coalescing: if more frames already queued
                # behind this one, merge the run into one bounded
                # writev batch — one syscall instead of one per frame
                nxt = self._wq.drain_one()
                if nxt is not None:
                    self._wq_acct_pop(nxt)
                    status = self._write_coalesced(data, cb, nxt)
                    if status == 0:
                        continue      # batch fully sent: keep draining
                    if status == 1:
                        return ok     # parked on the writable event
                    if status == 3:
                        return False  # queue claimed by a concurrent
                                      # set_failed: stop draining
                    ok = False        # batch failed (socket now failed)
                    continue
            if self.failed:
                err = self.fail_reason
            else:
                err, leftover = self._write_data_once(data)
                if err is None and leftover is not None:
                    # blocked mid-frame: park writership on the
                    # writable event
                    st = self._park_handoff(leftover, cb)
                    if st == 1:
                        return ok
                    if st == -1:
                        return False
                    ok = False
                    continue
            if err is not None:
                ok = False
                self.set_failed(err)
            if cb is not None:
                try:
                    cb(err)
                except Exception:
                    pass

    def _take_handoff(self):
        with self._handoff_lock:
            item, self._handoff = self._handoff, None
            if item is not None:
                # every taker disposes of the item immediately (resumes
                # the write or fails its callback): settle the gauge
                sz = item[0].size
                self.wq_bytes -= sz
                nwqueue_bytes.add(-sz)
        return item

    def _park_handoff(self, leftover, comp) -> int:
        """Park a blocked write remainder on the writable event (the
        continuation takes it via _take_handoff) — the ONE copy of the
        park protocol the single-frame, coalesced and ring write paths
        all share. The parked bytes re-enter the queue gauge: a
        stalled peer holding megabytes mid-frame is exactly what
        socket_wqueue_bytes exists to show (_take_handoff settles it
        when the park resolves).

        Returns 1 = parked; 0 = requesting the event failed (socket
        now failed, ``comp`` fired with the reason, writership still
        this context's — keep fail-draining); -1 = it failed AND a
        concurrent set_failed already claimed the handoff and
        writership (this context must NOT touch the queue again:
        draining here too would put two consumers on it)."""
        lsz = leftover.size
        with self._handoff_lock:
            self._handoff = (leftover, comp)
            self.wq_bytes += lsz
            nwqueue_bytes.add(lsz)
        try:
            self.conn.request_writable_event()
            return 1
        except Exception as e:
            took = self._take_handoff()
            self.set_failed(e if isinstance(e, Exception)
                            else ConnectionError(str(e)))
            if took is None:
                return -1
            if took[1] is not None:
                try:
                    took[1](self.fail_reason)
                except Exception:
                    pass
            return 0

    def _write_coalesced(self, data, cb, nxt) -> int:
        """Send a run of queued frames as ONE gather-write batch:
        ``data``/``cb`` plus ``nxt`` plus whatever else sits in the
        queue, up to the coalescing caps. Per-frame callbacks fire as
        their bytes are fully accepted; a blocked batch parks its
        remainder (with the unfired callbacks composited) through the
        same handoff protocol as a single frame. Device-ref-bearing
        IOBufs keep their semantics: refs merge in FIFO frame order,
        so the lane-batch pairing (write_device_payload immediately
        before its wire frame) cannot interleave.

        Returns 0 = batch fully sent (keep draining), 1 = parked on
        the writable event (writership parked), 2 = failed (socket is
        now failed; every callback fired with the reason), 3 = failed
        AND a concurrent set_failed claimed the queue (the caller must
        stop draining — two consumers otherwise)."""
        agg = IOBuf()
        marks = []                    # (end_offset, cb) per frame
        total = 0

        def add(d, c):
            nonlocal total
            if isinstance(d, IOBuf):
                agg.append_buf(d)
                total += d.size
            elif len(d):
                agg.append_user_data(d)
                total += len(d)
            marks.append((total, c))

        add(data, cb)
        add(nxt[0], nxt[1])
        while total < _COALESCE_MAX_BYTES and len(marks) < _COALESCE_MAX_FRAMES:
            more = self._wq.drain_one()
            if more is None:
                break
            self._wq_acct_pop(more)
            add(more[0], more[1])
        ncoalesced.add(len(marks) - 1)
        try:
            self._cut_buf(agg)        # gather writev; absorbs EAGAIN
        except (BrokenPipeError, ConnectionError, OSError) as e:
            self.set_failed(e)
            for _, c in marks:
                if c is not None:
                    try:
                        c(e)
                    except Exception:
                        pass
            return 2
        sent = total - agg.size
        pending_cbs = []
        for end, c in marks:
            if end <= sent:
                if c is not None:
                    try:
                        c(None)
                    except Exception:
                        pass
            elif c is not None:
                pending_cbs.append(c)
        if not agg:
            return 0
        # blocked mid-batch: park the remainder with the unfired
        # callbacks composited into one done (same protocol as the
        # single-frame park in _drain_writes_inline)
        comp = _composite_cb(pending_cbs)
        st = self._park_handoff(agg, comp)
        if st == 1:
            return 1
        return 3 if st == -1 else 2

    def ring_collect_writes(self):
        """Ring-flush collect half (ring thread; writership was claimed
        by the deferring push): drain queued frames into a flat list of
        buffer views plus per-frame callback marks for ONE native
        gather write. The coalescing caps bound what one writev pins,
        exactly like _write_coalesced. Returns (views, marks, total)."""
        views = []
        marks = []              # (end_offset, cb) per frame
        total = 0
        while total < _COALESCE_MAX_BYTES and \
                len(marks) < _COALESCE_MAX_FRAMES:
            item = self._wq.drain_one()
            if item is None:
                break
            self._wq_acct_pop(item)
            data, cb = item
            if isinstance(data, IOBuf):
                # rare on this lane (deferral only claims bytes frames,
                # but racing producers may queue IOBufs behind one):
                # flatten — fd conns carry no device refs, and the ring
                # batch is a small-frame lane
                data = data.to_bytes()
            if len(data):
                views.append(data)
                total += len(data)
            marks.append((total, cb))
        return views, marks, total

    def ring_settle_write(self, res: int, errcode: int, views, marks,
                          total: int) -> bool:
        """Ring-flush settle half: fire done callbacks for fully-sent
        frames, park a blocked remainder through the standard handoff
        protocol (writable-event continuation), fail the socket on real
        errors — the exact _write_coalesced contract, split so the
        syscall itself could run in the tick's native batch. Returns
        False when the socket failed."""
        if errcode:
            e = ConnectionError(
                f"ring writev: {os.strerror(errcode)}")
            self.set_failed(e)
            for _, cb in marks:
                if cb is not None:
                    try:
                        cb(e)
                    except Exception:
                        pass
            # stragglers queued behind the batch fail-drain through the
            # classic writer (we still hold writership), which retires
            self._drain_writes_inline()
            return False
        sent = res
        pending_cbs = []
        for end, cb in marks:
            if end <= sent:
                if cb is not None:
                    try:
                        cb(None)
                    except Exception:
                        pass
            elif cb is not None:
                pending_cbs.append(cb)
        if sent >= total:
            # batch fully sent: anything that queued meanwhile drains
            # classically, and try_retire releases writership
            self._drain_writes_inline()
            return True
        # blocked mid-batch: rebuild the unsent tail as zero-copy
        # user-data refs (only the straddled frame pays a slice) and
        # park it with the unfired callbacks composited — the same
        # protocol as _write_coalesced's status-1 exit
        leftover = IOBuf()
        off = 0
        for v in views:
            lv = len(v)
            if off + lv <= sent:
                off += lv
                continue
            start = sent - off if sent > off else 0
            leftover.append_user_data(v[start:] if start else v)
            off += lv
        st = self._park_handoff(leftover, _composite_cb(pending_cbs))
        if st == 1:
            return True
        if st == 0:
            # park failed but writership is still this context's (the
            # socket is now failed): fail-drain the stragglers queued
            # behind the batch so their callbacks fire with the reason
            # and try_retire releases writership — matching the errcode
            # branch above and _drain_writes_inline's st==0 handling
            self._drain_writes_inline()
        return False

    def probe_unobserved(self) -> bool:
        """True when this socket is (now) failed. A sticky pluck pause
        leaves NOTHING watching the fd between sync calls, so a peer
        FIN lands unseen — callers about to REUSE a socket (channel
        single/pooled pick) probe here: one non-consuming MSG_PEEK
        (only when the socket is actually in the unobserved state)
        restores the dead-peer detection the dispatcher's read event
        used to provide, BEFORE a call is issued into the corpse."""
        if self.failed:
            return True
        if not self._pluck_sticky:
            return False          # reads armed: the dispatcher watches
        if time.monotonic() - self._sticky_since < 0.005:
            # back-to-back sync calls: skip the probe syscall — a peer
            # close in a <5ms window still surfaces through the pluck
            # read itself, this probe exists for IDLE reuse
            return False
        peek = getattr(self.conn, "peek_closed", None)
        if peek is not None:
            try:
                if peek():
                    self.set_failed(ConnectionResetError("peer closed"))
                    return True
            except Exception:
                pass
        return False

    def unstick_reads(self) -> None:
        """Re-arm read interest left sticky-paused by a settled pluck
        (see _pluck_sticky). Idempotent; never touches a socket whose
        pause is owned by a live plucker or busy period."""
        with self._nevent_lock:
            if not self._pluck_sticky:
                return
            self._pluck_sticky = False
            if self._busy_paused and not self._plucking:
                self._busy_paused = False
                if not self.failed:
                    try:
                        self.conn.resume_read_events()
                    except Exception:
                        pass

    def write_device_payload(self, arrays, span=None) -> bool:
        """Out-of-band device lane (mem/tpu transports); host transports
        must serialize instead. ``span``: the owning RPC span — when
        device telemetry is on, the transfer gets a stage tracker (and,
        with rpcz, a child device span) stamped through the conn's
        flush/ack machinery; conns without tracker support settle the
        whole timeline synchronously around the call."""
        _ds = _device_stats
        tracker = None
        if _ds.enabled():
            conn = self.conn
            lane = getattr(conn, "lane_kind", None) or \
                getattr(conn.remote_endpoint, "scheme", "device")
            # (lane, peer, cell) cached on the socket — the PR 7
            # cells-cached-per-channel discipline; lane_kind can change
            # once the hello lands, so the cache keys on it
            cached = self.__dict__.get("_dev_send")
            if cached is None or cached[0] != lane:
                peer = _ds.peer_key(conn.remote_endpoint)
                cached = (lane, peer,
                          _ds.global_device_stats().device_cell(peer,
                                                                lane))
                self._dev_send = cached
            nbytes = sum(getattr(a, "nbytes", 0) or 0 for a in arrays)
            tracker = _ds.open_transfer(cached[1], lane, nbytes,
                                        parent_span=span,
                                        cell=cached[2])
        if tracker is not None and \
                getattr(self.conn, "supports_device_tracker", False):
            try:
                return bool(self.conn.write_device_payload(
                    arrays, tracker=tracker))
            except BaseException as e:
                # the conn's own failure paths settle the tracker for
                # the cases they detect (poison, unsendable) — but a
                # raise BEFORE those checks (device_put OOM, bad
                # dtype) must not strand an opened cell record; the
                # settle latch makes a double report harmless
                tracker.lane_failed(f"{type(e).__name__}: {e}")
                raise
        try:
            r = self.conn.write_device_payload(arrays)
        except BaseException as e:
            if tracker is not None:
                tracker.lane_failed(f"{type(e).__name__}: {e}")
            raise
        if tracker is not None:
            # loopback/staged conns deliver synchronously: the whole
            # timeline collapses into one settle (stage≈call, ack≈0)
            tracker.lane_encoded()
            tracker.lane_flushed()
            tracker.lane_acked()
        return bool(r)

    def _cut_buf(self, buf: IOBuf) -> None:
        """Write as much of the chain as the conn accepts: gather-write
        (one sendmsg per iovec batch) when available and worthwhile,
        per-ref writes otherwise. BlockingIOError is absorbed, leaving
        the remainder in ``buf``."""
        if self._writev is not None and buf.backing_block_count > 1:
            buf.cut_into_gather_writer(self._writev)
        else:
            buf.cut_into_writer(self.conn.write)

    async def _write_buf_blocking(self, buf: IOBuf) -> Optional[BaseException]:
        while buf and not self.failed:
            try:
                self._cut_buf(buf)
            except (BrokenPipeError, ConnectionError, OSError) as e:
                return e
            if buf:
                # blocked: arm one-shot writable event, park on butex
                seq = self._writable_butex.value
                self.conn.request_writable_event()
                await self._writable_butex.wait(expected=seq, timeout_s=1.0)
        if buf and self.failed:
            return self.fail_reason   # failed mid-write: not a success
        return None

    async def _keep_write(self):
        """Background writer (owns writership until retire): finishes a
        handed-off partial frame, then drains the queue, parking on the
        writable butex when the conn blocks (KeepWrite bthread,
        socket.cpp:2066-2160). On failure every remaining item's callback
        fires with the reason — never a silent drop."""
        handoff = self._take_handoff()
        if handoff is not None:
            buf, cb = handoff
            err = await self._write_buf_blocking(buf)
            if err is not None:
                self.set_failed(err)
            if cb is not None:
                try:
                    cb(err)
                except Exception:
                    pass
        while True:
            item = self._wq.drain_one()
            if item is None:
                if self._wq.try_retire():
                    return
                continue
            self._wq_acct_pop(item)
            data, cb = item
            err: Optional[BaseException] = None
            if self.failed:
                err = self.fail_reason
            else:
                if not isinstance(data, IOBuf):
                    b = IOBuf()
                    b.append(data)
                    data = b
                err = await self._write_buf_blocking(data)
                if err is not None:
                    self.set_failed(err)
            if cb is not None:
                try:
                    cb(err)
                except Exception:
                    pass

    def _on_writable_event(self):
        self._writable_butex.fetch_add(1)
        self._writable_butex.wake_all()
        if self._inline_write:
            item = self._take_handoff()
            if item is not None:
                # we now hold writership: resume the parked frame and
                # whatever queued behind it, right here
                self._drain_writes_inline(first_item=item)

    # -------------------------------------------------------------- input
    def ring_input(self, data, eof: bool = False, err: int = 0) -> None:
        """Ring-lane completion sink (ring dispatcher thread): the tick
        already recv'd ``data`` natively — queue it and run the
        standard input cycle with the fd drain suppressed. Mirrors
        _on_readable_event + _drain_readable with the recv replaced by
        a chunk handoff; the busy/_nevent protocol, EOF verdicts and
        escalation rules are shared, so the lanes cannot diverge on
        semantics (completion drain only schedules work — the
        graftlint ring-entrypoint contract)."""
        self.last_active_ns = time.monotonic_ns()
        if data:
            nreads.add(len(data))
        with self._nevent_lock:
            if data:
                self._ring_chunks.append(data)
            self._nevent += 1
            busy = self._nevent > 1 or self._plucking
            if not busy:
                self._ring_fed = True
            elif data and self._level_triggered and not self._busy_paused:
                # busy period with data still arriving: pause ring read
                # interest for the rest of it, exactly like the classic
                # level-trigger path (same lock, same flag — the resume
                # in _finish_input_cycle cannot disagree)
                self._busy_paused = True
                try:
                    self.conn.pause_read_events()
                except Exception:
                    self._busy_paused = False
        if eof or err:
            e = (ConnectionResetError("peer closed") if eof
                 else ConnectionError(f"ring recv: {os.strerror(err)}"))
            if busy:
                # the owning pass may be SUSPENDED awaiting a handler;
                # the failure must not wait for it, and set_failed runs
                # user callbacks — keep them off the event thread (the
                # classic peek path's discipline)
                self._control.spawn(lambda: self.set_failed(e))
                return
            self.set_failed(e)   # inline: the drain's own verdict path
        if busy:
            return
        if self._inline_process:
            if self._on_input_sync is not None:
                self._process_input_entry()
            else:
                self._control.run_inline(self._process_input(),
                                         name="socket_input")
        else:
            self._control.spawn(self._process_input, name="socket_input")

    def _on_readable_event(self):
        """May fire from the dispatcher thread or a peer's fiber; only the
        0->1 transition starts a processing fiber."""
        self.last_active_ns = time.monotonic_ns()
        with self._nevent_lock:
            self._nevent += 1
            # a plucking joiner owns the input: events defer to it
            # exactly like a busy processing pass
            busy = self._nevent > 1 or self._plucking
        if not busy:
            if self._inline_process:
                if self._on_input_sync is not None:
                    # fully-sync fast path: no coroutine, no Fiber —
                    # escalates itself if a message's processing awaits
                    self._process_input_entry()
                else:
                    # zero-wake fast path: drain + parse + dispatch on
                    # THIS thread; suspension continues as a fiber
                    self._control.run_inline(self._process_input(),
                                             name="socket_input")
            else:
                self._control.spawn(self._process_input, name="socket_input")
            return
        # the input fiber is busy — possibly SUSPENDED awaiting a long
        # handler, in which case it cannot drain this event for a
        # while. A dead peer must still become visible NOW
        # (Controller::IsCanceled / NotifyOnCancel): cheap non-consuming
        # EOF probe from the dispatcher (the reference's event
        # dispatcher detects the hangup independently of message
        # processing for the same reason)
        peek = getattr(self.conn, "peek_closed", None)
        if peek is not None:
            try:
                if peek():
                    # NOT inline: set_failed runs user notify_on_cancel
                    # callbacks — a blocking one must not stall the
                    # process-wide dispatcher thread (the reference runs
                    # NotifyOnCancel in a fresh bthread)
                    self._control.spawn(
                        lambda: self.set_failed(
                            ConnectionResetError("peer closed")))
                elif self._level_triggered:
                    # data (not FIN) pending while the input context is
                    # busy: a LEVEL-triggered fd would re-fire this
                    # event in a hot loop — pause read interest for the
                    # rest of the busy period (the input loop re-drains
                    # via _nevent, and the busy-period end resumes).
                    # Flag AND fd state change under ONE _nevent_lock
                    # hold, and only while a processing pass is still
                    # owed (_nevent > 0): otherwise this pause could
                    # race the busy period ending and leave the fd
                    # deaf forever (no one left to resume). The
                    # matching resume in _finish_input_cycle also runs
                    # under the lock, so flag and fd state never
                    # disagree. This is the only read-interest syscall
                    # pair left on the TCP path; the idle/inline common
                    # case pays none
                    with self._nevent_lock:
                        if self._nevent > 0 and not self._busy_paused:
                            self._busy_paused = True
                            self.conn.pause_read_events()
                elif not self._busy_rearmed:
                    # one-shot conns (ssl): this event consumed the read
                    # interest — re-arm so a later FIN during the same
                    # handler still produces an event. ONCE per busy
                    # period: unconditional re-arm with data pending
                    # would storm the dispatcher, and the input loop
                    # re-drains pending data anyway via _nevent
                    self._busy_rearmed = True
                    resume = getattr(self.conn, "resume_read_events", None)
                    if resume is not None:
                        resume()
            except Exception:
                pass

    def _input_error(self, e: BaseException) -> None:
        # an escaping parse/process error must not wedge the socket (a
        # dead processing context would leave _nevent elevated and no
        # future event would restart it): drop the conn
        import logging
        logging.getLogger("brpc_tpu.transport").exception(
            "input processing failed; dropping connection")
        self.set_failed(e if isinstance(e, Exception)
                        else ConnectionError(str(e)))

    def _finish_input_cycle(self, pending: int) -> bool:
        """Settle one drain+dispatch cycle; True = more events arrived
        (caller loops)."""
        with self._nevent_lock:
            self._nevent -= pending
            if self._nevent > 0:
                return True
            self._busy_rearmed = False   # busy period over
            self._pluck_sticky = False   # a live busy period owns the
            #                              pause again: never leave the
            #                              flag claiming otherwise
            if self._busy_paused and not self._plucking:
                # paired with the pause in _on_readable_event: both run
                # under the lock so the paused flag always matches the
                # fd's read-interest state. While a plucker owns the fd
                # the pause STAYS (resuming here would reinstate the
                # per-message dispatcher wakes the claim-time pause
                # removed); the pluck exit path restores read interest.
                self._busy_paused = False
                if not self.failed:
                    try:
                        self.conn.resume_read_events()
                    except Exception:
                        pass
        return False

    def _pluck_process(self):
        """One drain+process pass in the pluck context. Returns True
        when the pass ESCALATED (a message's processing suspended — the
        cycle, including pending-event accounting, was handed back to
        the normal machinery and the caller must stop plucking)."""
        with self._nevent_lock:
            pending = self._nevent
        self._drain_readable()
        if self.input_portal or self.failed:
            r = None
            try:
                r = self._on_input_sync(self)
            except BaseException as e:
                self._input_error(e)
            if r is not None:
                # The extra _nevent keeps the busy invariant (>=1
                # through the handoff): with pending==0 a dispatcher
                # event in this window would otherwise start a
                # CONCURRENT processing pass against the same portal
                # as the escalated tail
                with self._nevent_lock:
                    self._nevent += 1
                    self._plucking = False
                self._control.run_inline(
                    self._input_async_tail(r, pending + 1),
                    name="socket_input")
                return True
        if pending:
            self._finish_input_cycle(pending)
        return False

    def pluck_preclaim(self) -> bool:
        """Claim the sync-pluck lane BEFORE the request is sent: pausing
        read interest pre-send closes the 1-core race where the kernel
        runs the server and then the dispatcher before the issuing
        thread resumes — the response would complete on the dispatcher
        (cross-thread wake, event-wait join) on roughly a coin flip.
        Returns True when claimed; the caller MUST hand the claim to
        pluck_until(preclaimed=True) or release via pluck_release()."""
        if getattr(self.conn, "pluck_fd", None) is None \
                or self._on_input_sync is None or self.failed:
            return False
        if self._ring_attached and not self._ring_pluck_ok:
            # uring backend: an in-flight kernel RECV cannot be fenced
            # synchronously — sync joins keep the event-driven path
            return False
        with self._nevent_lock:
            if self._nevent > 0 or self._plucking:
                return False
            self._plucking = True
            # a sticky pause from the previous settle is consumed here:
            # read interest is already off, so the claim pays NO
            # epoll_ctl (the steady sync-RPC state)
            self._pluck_sticky = False
            reads_were_live = not self._busy_paused
            if self._level_triggered and not self._busy_paused:
                self._busy_paused = True
                try:
                    self.conn.pause_read_events()
                except Exception:
                    self._busy_paused = False
        if self._ring_attached and reads_were_live:
            # reads were armed on the ring: fence the in-flight tick so
            # its native pass cannot consume the response this claim is
            # about to solicit (steady-state sticky claims skip — reads
            # were already off, the ring never had the fd armed). The
            # barrier runs OUTSIDE _nevent_lock: the tick may be
            # delivering to this very socket's ring_input right now.
            rb = getattr(self.conn, "ring_read_barrier", None)
            if rb is not None:
                rb()
            if self._ring_chunks:
                # bytes the ring stole before the fence (pre-request
                # pipelined tails): we own processing now — move them
                # into the portal so the pluck lanes judge them
                with self._nevent_lock:
                    chunks, self._ring_chunks = self._ring_chunks, []
                for c in chunks:
                    self.input_portal.append_user_data(c)
        return True

    def pluck_release(self) -> None:
        """THE pluck-claim settle protocol, shared by pluck_until's exit
        and every path that abandons a pluck_preclaim (retry moved the
        call to another socket, the joiner never arrived). Pause flag
        and fd read-interest change under the same lock as the claim,
        so they can never disagree; deferred events we didn't settle
        get one normal pass (its finish cycle restores read interest
        and balances the _nevent accounting)."""
        with self.pending_lock:
            # pending_lock FIRST (established order: pending -> nevent):
            # the sticky decision below reads client_inflight, and it
            # must serialize against _set_issue_socket registrations —
            # either the registration lands first (we see it and
            # resume) or we stick first (the issuer's write sees the
            # sticky flag and unsticks). No window hangs a response.
            with self._nevent_lock:
                if not self._plucking:
                    return
                self._plucking = False
                leftover = self._nevent > 0
                if self._busy_paused and not leftover:
                    if (not self.failed and self.client_inflight == 0
                            and not self.user_data.get("bound_streams")):
                        # sticky pause: nothing in flight can produce
                        # input — leave reads off so the next sync call
                        # claims the lane for free (unstick_reads is
                        # every non-pluck consumer's entry)
                        self._pluck_sticky = True
                        self._sticky_since = time.monotonic()
                    else:
                        self._busy_paused = False
                        if not self.failed:
                            try:
                                self.conn.resume_read_events()
                            except Exception:
                                pass
        if leftover and not self.failed:
            self._process_input_entry()

    # graftlint: disable=judge-defer -- the defer exit here is
    # re-injection, not a return: frames the native loop can't judge are
    # appended back into input_portal and settled through the classic
    # machinery before pluck_until returns pred()
    def pluck_until(self, pred, deadline_s: float, fast=None,
                    preclaimed: bool = False) -> bool:
        """Sync-pluck lane: a joining (non-worker) thread adopts this
        socket's input processing until ``pred()`` or the deadline — the
        caller waiting for its response drives the connection itself,
        paying zero cross-thread wakes and no dispatcher round trip per
        message (the pthread analog of the reference's in-place bthread
        processing; gRPC core's completion-queue pluck is the same
        idea). Claims the socket only when no processing pass is in
        flight; for the duration, dispatcher events defer to the
        plucker (``_plucking`` reads as busy), and leftovers are
        settled through the normal machinery on exit. Returns pred().

        ``fast=(magic, cid, max_body, on_resp)`` arms the native receive
        loop
        (fastcore pluck_scan): poll+recv+frame-scan run in ONE C call
        per slice, and the sole expected response completes through
        ``on_resp(cid, ec, et, payload, att, sock)``. Anything only the
        classic path can judge (foreign frames, slow metas, pipelined
        tails) is re-injected into the portal and processed through the
        normal machinery — the lanes cannot diverge on semantics."""
        # ONE claim protocol (pluck_preclaim) and ONE settle protocol
        # (pluck_release) shared with the pre-send claim path — the
        # lock-sensitive pause/resume dance must not exist twice
        if not preclaimed and not self.pluck_preclaim():
            return pred()   # can't pluck / processing in flight
        pfd = getattr(self.conn, "pluck_fd", None)
        if pfd is None or self._on_input_sync is None:
            self.pluck_release()
            return pred()
        try:
            fd = pfd()
        except OSError:
            self.pluck_release()
            return pred()
        scan = None
        dup_fd = -1
        if fast is not None and not self.input_portal and \
                not self.input_need and not self._ring_chunks:
            fc = _fastcore()
            scan = getattr(fc, "pluck_scan", None) if fc is not None else None
            if scan is not None:
                # pinned fd: the refcounted cached dup (pin_fd_acquire)
                # pins the kernel socket for the loop's duration — same
                # fd-recycling protection as a per-call dup, without
                # the dup+close syscall pair on every sync RPC
                dup_fd = self.pin_fd_acquire()
                if dup_fd < 0:
                    scan = None
        poller = None
        escalated = False
        carry = b""
        try:
            while not pred() and not self.failed:
                remaining = deadline_s - time.monotonic()
                if remaining <= 0:
                    break
                if self._ring_chunks and not carry:
                    # belt and braces: a ring completion slipped past
                    # the preclaim fence (uring cross-tick tail) —
                    # those bytes precede anything still in the kernel,
                    # so the classic machinery must judge them first,
                    # and the native scan stands down (a partial frame
                    # left in the portal must not have its tail read
                    # into the scan's carry out of order)
                    scan = None
                    escalated = self._pluck_process()
                    if escalated:
                        break
                    continue
                # short slices: pred() can flip without fd traffic
                # (timeout timer, another thread completing the call)
                if scan is not None:
                    magic, cid, max_body, on_resp = fast
                    r = scan(dup_fd, magic, cid,
                             int(min(remaining, 0.2) * 1000) + 1,
                             max_body, carry)
                    tag = r[0]
                    nr = r[-1]            # bytes the C loop read this call
                    if nr:
                        nreads.add(nr)
                    if tag == 2:          # slice elapsed: keep the carry
                        carry = r[1]
                        continue
                    carry = b""
                    if tag == 0:          # the response for cid
                        npluck_fast.add(1)
                        # this completion bypasses record_dispatch_batch
                        # (the other denominator authority): count it
                        # here so syscalls_per_rpc stays honest on the
                        # sync-pluck lane
                        _syscall_stats.note_rpc_messages(1)
                        _, ec, et, payload, att, leftover, _nr = r
                        if leftover:
                            self.input_portal.append_user_data(leftover)
                        on_resp(cid, ec, et, payload, att, self)
                        if not self.input_portal:
                            continue      # pred() flips on the next check
                        # pipelined tail behind our response: classic
                        # machinery from here (retry may change cid)
                        scan = None
                        escalated = self._pluck_process()
                        if escalated:
                            break
                        continue
                    if tag == 1:          # defer: classic path judges
                        npluck_defer.add(1)
                        if r[1]:
                            self.input_portal.append_user_data(r[1])
                        scan = None
                        escalated = self._pluck_process()
                        if escalated:
                            break
                        continue
                    # tag == 3: EOF/socket error; complete frames that
                    # arrived before it still get processed, exactly as
                    # the classic drain would
                    scan = None
                    if r[2]:
                        self.input_portal.append_user_data(r[2])
                        escalated = self._pluck_process()
                        if escalated:
                            break
                    if not self.failed and not pred():
                        self.set_failed(ConnectionError(r[1]))
                    continue
                if poller is None:
                    import select
                    poller = self.__dict__.get("_pluck_poller")
                    if poller is None:
                        poller = self._pluck_poller = select.poll()
                        poller.register(
                            fd,
                            select.POLLIN | select.POLLHUP | select.POLLERR)
                if not poller.poll(min(remaining, 0.2) * 1000):
                    continue
                escalated = self._pluck_process()
                if escalated:
                    break
        finally:
            if dup_fd >= 0:
                self.pin_fd_release()
            if carry:
                # a partial frame read by the native loop: back into the
                # portal — more bytes must arrive for it to complete, and
                # their readable event restarts normal processing
                self.input_portal.append_user_data(carry)
            if not escalated:
                # the shared settle (escalation already handed the
                # claim + accounting to the normal machinery)
                self.pluck_release()
        return pred()

    def _process_input_entry(self) -> None:
        """Sync processing loop (no coroutine, no Fiber); when a
        message's processing turns out to be async, the remainder of
        the cycle escalates to a fiber via run_inline."""
        while True:
            with self._nevent_lock:
                pending = self._nevent
            fde = self.fast_drain
            if fde is not None and not self.failed:
                handled = False
                try:
                    handled = fde(self)
                except BaseException as e:
                    self._input_error(e)
                if handled:
                    if not self._finish_input_cycle(pending):
                        return
                    continue
            self._drain_readable()
            if self.input_portal or self.failed:
                r = None
                try:
                    r = self._on_input_sync(self)
                except BaseException as e:
                    self._input_error(e)
                if r is not None:
                    self._control.run_inline(
                        self._input_async_tail(r, pending),
                        name="socket_input")
                    return
            if not self._finish_input_cycle(pending):
                return

    async def _input_async_tail(self, r, pending: int):
        """Finish an escalated cycle: await the pending processing, then
        continue the event loop in async mode."""
        try:
            await r
        except BaseException as e:
            self._input_error(e)
        if self._finish_input_cycle(pending):
            await self._process_input()

    async def _process_input(self):
        while True:
            with self._nevent_lock:
                pending = self._nevent
            self._drain_readable()
            if self._on_input is not None and (self.input_portal or self.failed):
                try:
                    r = self._on_input(self)
                    if hasattr(r, "__await__"):
                        await r
                except BaseException as e:
                    self._input_error(e)
            if not self._finish_input_cycle(pending):
                return

    def _drain_readable(self) -> int:
        """Read until EAGAIN/EOF into the portal; returns bytes read.

        Read blocks are sized adaptively: full reads grow the next
        block (up to 256KB) so bulk transfers take few recv syscalls,
        small reads shrink it back so idle connections don't hold large
        buffers — the readv-into-many-blocks effect of
        iobuf.h:469 without the iovec."""
        if self._ring_fed or self._ring_attached or self._ring_chunks:
            # ring lane: the dispatcher tick is the ONLY recv authority
            # for this fd — this pass consumes what it queued (ordered:
            # one appender, moved here by the one owning processing
            # context). _ring_fed guards the birth race where a
            # completion lands before __init__ stamps _ring_attached.
            self._ring_fed = False
            with self._nevent_lock:
                chunks, self._ring_chunks = self._ring_chunks, []
            total = 0
            portal = self.input_portal
            for c in chunks:
                portal.append_user_data(c)
                total += len(c)
            if not (self._plucking and self._busy_paused):
                return total
            # pluck claim: preclaim paused ring reads AND fenced the
            # in-flight tick (read_barrier), so the ring can no longer
            # touch this fd — the PLUCKING context is the recv
            # authority now. Everything the pluck lane routes through
            # the classic machinery (a response past the scan's
            # max_body, a large-request call that never armed the
            # scan) reaches here, and without the fd drain below those
            # bytes would sit in the kernel forever while pluck_until
            # busy-polls readiness. Queued chunks went first (they
            # were recv'd before anything the kernel still holds), so
            # order is preserved; outside the claim the suppression
            # above stands — an unfenced in-flight tick may hold an
            # undelivered chunk, and an fd read here would land behind
            # it out of order.
            ring_total = total
        else:
            ring_total = 0
        rc = self._read_chunks
        if rc is not None:
            # zero-copy handoff (mem://): the writer's bytes objects
            # become user-data blocks directly — no read_into copy, no
            # block management
            chunks, eof = rc()
            if eof:
                self.set_failed(ConnectionResetError("peer closed"))
                return 0
            total = 0
            portal = self.input_portal
            for c in chunks:
                portal.append_user_data(c)
                total += len(c)
            if total:
                nreads.add(total)
            return total
        total = ring_total
        while not self.failed:
            hint = self._read_hint
            try:
                if self._readv is not None and hint >= _BIG_BLOCK_SIZE:
                    # bulk mode: scatter-read a whole burst per syscall
                    n = self.input_portal.append_from_reader_v(
                        self._readv, hint=hint, nbufs=4)
                else:
                    n = self.input_portal.append_from_reader(
                        self.conn.read_into, hint=hint)
            except BlockingIOError:
                # drained. One-shot conns re-arm here (the event consumed
                # their read interest). Level-triggered conns must NOT:
                # their arming is owned by the pause/resume busy protocol
                # — an EAGAIN rearm mid-pause would defeat the pause and
                # let the fd re-fire hot for the rest of the busy period
                if not self._level_triggered:
                    resume = getattr(self.conn, "resume_read_events", None)
                    if resume is not None:
                        resume()
                break
            except (ConnectionError, OSError) as e:
                self.set_failed(e)
                break
            if n == 0:  # EOF
                self.set_failed(ConnectionResetError("peer closed"))
                break
            if n >= hint:
                # jump straight to the big recyclable size: intermediate
                # sizes would allocate non-poolable buffers
                self._read_hint = _BIG_BLOCK_SIZE
            elif n < 4096:
                self._read_hint = DEFAULT_BLOCK_SIZE
            total += n
            nreads.add(n)
            if self._drain_all_reads and self.conn.pending_bytes() == 0:
                # exact emptiness probe (a short read is NOT proof —
                # the read may have landed in a small tail-block gap):
                # stop without paying a raise/catch of BlockingIOError
                # per message. Safe only because such conns notify on
                # every write, so a refill re-triggers _process_input.
                break
            if self._level_triggered and n < 4096:
                # short read on a level-triggered fd: almost certainly
                # drained — skip the EAGAIN recv round trip. 4096 is
                # below every buffer this loop offers (fresh blocks are
                # >=8KB; tail gaps <4KB are never offered), so a short
                # read really was short. If the kernel does hold more,
                # the level trigger fires again — no stall possible.
                break
        return total

    def take_device_payload(self):
        take = getattr(self.conn, "take_device_payload", None)
        if take is None:
            return None
        _ds = _device_stats
        if not _ds.enabled():
            return take()
        t0 = time.monotonic_ns()
        lane = take()
        if lane is None:
            return None
        dur_us = (time.monotonic_ns() - t0) / 1e3
        conn = self.conn
        kind = getattr(conn, "lane_kind", None) or \
            getattr(conn.remote_endpoint, "scheme", "device")
        cached = self.__dict__.get("_dev_recv")
        if cached is None or cached[0] != kind:
            peer = _ds.peer_key(conn.remote_endpoint)
            cached = (kind, peer,
                      _ds.global_device_stats().device_cell(peer, kind))
            self._dev_recv = cached
        nbytes = sum(getattr(a, "nbytes", 0) or 0 for a in lane)
        cached[2].note_recv(dur_us, nbytes)
        if flag("rpcz_enabled"):
            # parse-path handoff: the protocol attaches this to the
            # message so dispatch can hang a device-recv child span off
            # the server span it is about to create (parse per conn is
            # sequential — the slot cannot be clobbered before the
            # attach); only rpcz consumers read it, so only they pay
            # the dict
            self.last_device_take = {
                "peer": cached[1], "lane": kind,
                "recv_us": round(dur_us, 1),
                "nbytes": nbytes, "t_us": t0 // 1000}
        return lane

    def take_device_payload_with_recv(self):
        """(lane_arrays_or_None, recv_record_or_None) — the ONE parse-
        side consumer API: every protocol parse site uses this so the
        take + recv-record handoff cannot drift per protocol (the
        device-recv span's producing half)."""
        lane = self.take_device_payload()
        if lane is None:
            return None, None
        return lane, self.__dict__.pop("last_device_take", None)

    # ------------------------------------------------------------ failure
    def set_failed(self, reason: Optional[BaseException] = None) -> None:
        """Version-bump the id (outstanding SocketIds go stale), close the
        conn, fire failure callbacks (SetFailed, socket.cpp)."""
        with self._failed_cb_lock:
            if self.failed:
                return
            self.failed = True
            self.fail_reason = reason or ConnectionError("socket set_failed")
            cbs = list(self._on_failed_cbs)
        _pool().remove(self.id)
        try:
            self.conn.close()
        except Exception:
            pass
        # the pinned dup (native fd loops) must not outlive the close —
        # it would silently delay the FIN; closed now or by the last
        # pin_fd_release still in flight
        self._pin_fd_shutdown()
        self._writable_butex.fetch_add(1)
        self._writable_butex.wake_all()
        # a writer parked on a writable event will never be woken by the
        # closed conn: claim its handoff (the take IS the writership
        # transfer — the event continuation that loses the race no-ops)
        # and fail-drain it plus everything queued behind it
        item = self._take_handoff()
        if item is not None:
            self._drain_writes_inline(first_item=item)
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass
        self._drain_inflight_calls()

    def _drain_inflight_calls(self) -> None:
        """Fail (or retry elsewhere) every client call still issued on
        this socket — the reference errors all correlation ids bound to
        a failed Socket immediately (SetFailed -> bthread_id_error, so
        waiters never sit out the full RPC deadline on a dead
        connection). The failer is installed by the RPC layer
        (inflight_failer); it runs on a fiber because retries may
        reconnect (blocking), which must not run on the event thread."""
        failer = inflight_failer
        if failer is None:
            return
        with self.pending_lock:
            if not self.inflight_calls:
                return
            # correlation id AND issue sequence captured NOW: the failer
            # fiber judges the attempt that was bound to THIS socket —
            # a controller recycled onto a new call (cid changes) or
            # re-issued by a faster failure path (seq changes; transport
            # retries keep the cid) cannot be spuriously judged
            calls = [(c, c.correlation_id, c.__dict__.get("_issue_seq"))
                     for c in self.inflight_calls]
            self.inflight_calls.clear()
        self._control.spawn((lambda s=self, cs=calls: failer(s, cs)),
                            name="inflight_fail")

    def on_failed(self, cb: Callable[["Socket"], None]) -> None:
        # flag-check and append under one lock: a registration racing
        # set_failed's snapshot would otherwise be lost forever
        # (notify_on_cancel waiters would never fire)
        with self._failed_cb_lock:
            if not self.failed:
                self._on_failed_cbs.append(cb)
                return
        cb(self)

    def off_failed(self, cb: Callable[["Socket"], None]) -> None:
        """Unsubscribe a failure callback (no-op if absent): long-lived
        multiplexed sockets must not accumulate dead subscribers."""
        with self._failed_cb_lock:
            try:
                self._on_failed_cbs.remove(cb)
            except ValueError:
                pass


def create_client_socket(ep: EndPoint, on_input: Optional[Callable] = None,
                         control: Optional[TaskControl] = None) -> Socket:
    conn = get_transport(ep.scheme).connect(ep)
    return Socket(conn, on_input=on_input, control=control)
