"""InputMessenger: cuts complete messages out of a socket's byte stream by
trying registered protocols' Parse functions (brpc/input_messenger.{h,cpp}).

Keeps the reference's two hot-path tricks: the per-socket preferred
protocol index (first successful parser is remembered,
input_messenger.cpp:219), and in-place processing of the *last* message
while earlier ones get fresh fibers (QueueMessage, :183 — so a pipelined
burst parallelizes but the common single-message case pays no extra
handoff).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional

from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.bvar.reducer import Adder, Maxer, PassiveStatus
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.protocol.registry import PARSE_OK, PARSE_NOT_ENOUGH_DATA, PARSE_TRY_OTHERS, get_protocols
from brpc_tpu.transport import syscall_stats as _syscall_stats
from brpc_tpu.transport.socket import Socket

# Run-to-completion budget for a pipelined burst: up to this many
# messages of one dispatcher wakeup process IN the dispatch context
# (each still escalates to a fiber the moment it suspends — only the
# sync leg runs inline), anything past it spills to fibers with ONE
# amortized parking-lot signal (TaskControl.spawn_many). The budget
# bounds how long a burst of sync handlers can hold the event thread.
define_flag("dispatch_inline_budget", 16,
            "messages of one input burst processed in the dispatch "
            "context before the rest spill to fibers (single batch "
            "wake); suspending handlers escalate immediately")

# dispatch batch size: messages the Python dispatch loop settled per
# dispatcher wakeup cycle (native echo-serve batches are accounted
# separately via rpc server native batch counters). Windowed avg/peak
# on /vars + prometheus + the /status saturation pane.
_batch_msgs = Adder().expose("dispatch_batch_msgs")
_batch_cycles = Adder().expose("dispatch_batches")
_batch_peak = Maxer()
_batch_windows = None


def _batch_window_views():
    """(msgs_per_s, cycles_per_s, peak_window), created on first scrape
    (a Window registers with the background sampler thread)."""
    global _batch_windows
    if _batch_windows is None:
        from brpc_tpu.bvar.window import PerSecond, Window
        _batch_windows = (PerSecond(_batch_msgs, 10),
                          PerSecond(_batch_cycles, 10),
                          Window(_batch_peak, 10))
    return _batch_windows


def dispatch_batch_avg_10s() -> float:
    """Windowed mean messages per dispatch cycle (1.0 = no batching)."""
    msgs, cycles, _ = _batch_window_views()
    c = cycles.get_value() or 0
    if not c:
        return 0.0
    return round((msgs.get_value() or 0) / c, 2)


def dispatch_batch_peak_10s() -> int:
    _, _, peak = _batch_window_views()
    return peak.get_value() or 0


def _postfork_reset() -> None:
    """Fork hygiene: the window views are registered with the parent's
    sampler series; recreate them against the child's sampler."""
    global _batch_windows
    _batch_windows = None


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singleton it resets)

_postfork.register("transport.input_messenger", _postfork_reset)


PassiveStatus(dispatch_batch_avg_10s).expose("dispatch_batch_size_avg_10s")
PassiveStatus(dispatch_batch_peak_10s).expose("dispatch_batch_size_peak_10s")


def record_dispatch_batch(n: int) -> None:
    _batch_msgs.add(n)
    _batch_cycles.add(1)
    _batch_peak.update(n)
    # syscalls_per_rpc denominator (transport/syscall_stats.py): every
    # message this authority dispatches — requests AND responses, so a
    # loopback process counts both sides of each call
    _syscall_stats.note_rpc_messages(n)


async def _counted_dispatch(socket, work):
    """Run a queued message's processing with the socket's
    pending_responses claimed for its WHOLE lifetime — a spawned
    request that hasn't started yet must already be visible to the
    cut-through gate, or its response could interleave mid-stream."""
    try:
        r = work() if callable(work) else work
        if hasattr(r, "__await__"):
            await r
    finally:
        with socket.pending_lock:
            if socket.pending_responses > 0:
                socket.pending_responses -= 1


def counted_spawn(control, socket, work, name: str) -> None:
    """Spawn queued-message processing under a pending_responses claim
    (claimed HERE, at queue time, not at coroutine start). ``work`` is
    a zero-arg callable or an awaitable. Sockets that can never enter
    cut-through (no native-echo server) skip the claim entirely."""
    from brpc_tpu.rpc.server_dispatch import _track_pending
    if not _track_pending(socket):
        control.spawn(work, name=name)   # spawn runs callables/awaitables
        return
    with socket.pending_lock:
        socket.pending_responses += 1
    control.spawn(_counted_dispatch(socket, work), name=name)


def counted_spawn_many(control, socket, works, name: str) -> None:
    """Batch twin of counted_spawn: every work's claim lands before any
    fiber can start, and the whole spill pays ONE parking-lot signal
    (TaskControl.spawn_many)."""
    from brpc_tpu.rpc.server_dispatch import _track_pending
    if not _track_pending(socket):
        control.spawn_many(works, name=name)
        return
    with socket.pending_lock:
        socket.pending_responses += len(works)
    control.spawn_many([_counted_dispatch(socket, w) for w in works],
                       name=name)


def counted_run_inline(control, socket, work, name: str) -> None:
    """Process one queued message IN the dispatch context under its
    pending claim (run-to-completion: the sync leg runs right here
    with zero wakes; the first real suspension parks the remainder as
    a normal fiber). The budgeted middle of a pipelined burst."""
    from brpc_tpu.rpc.server_dispatch import _track_pending
    if not _track_pending(socket):
        control.run_inline(_drive(work), name=name)
        return
    with socket.pending_lock:
        socket.pending_responses += 1
    control.run_inline(_counted_dispatch(socket, work), name=name)


async def _drive(work):
    r = work() if callable(work) else work
    if hasattr(r, "__await__"):
        await r


class InputMessenger:
    def __init__(self, protocols: Optional[List] = None,
                 control: Optional[TaskControl] = None):
        self._protocols = protocols  # None = global registry snapshot per call
        self._control = control or global_control()

    def protocols(self) -> List:
        return self._protocols if self._protocols is not None else get_protocols()

    async def on_new_messages(self, socket: Socket):
        """The socket's input callback: parse-loop the portal, dispatch."""
        r = self.on_new_messages_sync(socket)
        if r is not None:
            await r

    def on_new_messages_sync(self, socket: Socket):
        """Sync twin of on_new_messages: parses and dispatches entirely
        on the calling context; returns a pending coroutine only when
        the LAST message's processing is async (the caller decides how
        to run it — Socket's sync input path run_inlines it, the async
        wrapper above awaits it). A fully-sync cycle (the client
        response path, pure stream frames) touches no coroutine or
        fiber machinery at all."""
        protocols = self.protocols()
        # mid-frame short-circuit: the previous cycle's parse told us
        # how many bytes the frame needs — until they're here, nothing
        # below can make progress (input_messenger.cpp keeps the same
        # cut-size memo between reads)
        need = socket.input_need
        if need:
            if socket.input_portal.size < need:
                return None
            socket.input_need = 0
        idx = socket.preferred_protocol
        if 0 <= idx < len(protocols):
            proto = protocols[idx]
            # turbo lane: one native call cuts + meta-decodes the whole
            # pending burst of small tpu_std frames, and the records
            # dispatch through the slim fast paths (the native per-call
            # loop; scan_frames in fastcore.cc)
            ts = getattr(proto, "turbo_scan", None)
            if ts is not None:
                portal = socket.input_portal
                # a large-frame echo in flight: forward the newly
                # arrived body bytes first (cut-through serving)
                cut = socket.user_data.get("_cut_forward")
                if cut is not None:
                    if not proto.cut_forward(portal, socket, cut):
                        return None          # mid-frame: await more bytes
                # scan the WHOLE portal before dispatching (the classic
                # loop's discipline — dispatch decisions like "earlier
                # messages get fresh fibers" need the full burst view);
                # the loop matters on chunk-handoff transports (mem://)
                # where each frame sits in its own block and one scan
                # only sees the head block
                all_recs = None
                nserve = getattr(proto, "native_serve", None)
                ncut = getattr(proto, "try_cut_through", None)
                mid_frame = False
                while True:
                    # echo-class front runs serve entirely in C (one
                    # scan+pack call, one write)
                    if nserve is not None and nserve(portal, socket):
                        if not portal:
                            break
                        continue
                    # large echo frames stream through without assembly
                    # — only when no undispatched requests sit ahead
                    # (their responses must leave first)
                    if ncut is not None and all_recs is None and \
                            ncut(portal, socket):
                        if socket.user_data.get("_cut_forward") is not None:
                            mid_frame = True
                            break
                        continue
                    recs = ts(portal, socket)
                    if not recs:
                        break
                    if all_recs is None:
                        all_recs = recs
                    else:
                        all_recs.extend(recs)
                    if not portal:
                        break    # fully consumed: skip the empty rescan
                if mid_frame:
                    return None
                if all_recs:
                    record_dispatch_batch(len(all_recs))
                    tail = proto.turbo_dispatch(all_recs, socket)
                    if not socket.input_portal:
                        return tail
                    if tail is not None:
                        # leftover (slow) bytes still need the classic
                        # loop below; the fallback tail becomes a fiber
                        counted_spawn(self._control, socket, tail,
                                      "process_tpu_std")
        # single-message fast path: a connection already claimed by a
        # protocol, one complete frame waiting (the overwhelmingly common
        # non-pipelined case) — parse and process directly, skipping the
        # candidate-ordering machinery below (the reference's
        # preferred_index + process-in-place discipline,
        # input_messenger.cpp:219,183)
        if 0 <= idx < len(protocols):
            proto = protocols[idx]
            status, msg = proto.parse(socket.input_portal, socket)
            if status == PARSE_OK and not socket.input_portal:
                record_dispatch_batch(1)
                if not proto.process_inline(msg, socket):
                    r = proto.process(msg, socket)
                    if r is not None and hasattr(r, "__await__"):
                        return r
                return None
            if status == PARSE_NOT_ENOUGH_DATA:
                return None
            if status == PARSE_OK:
                # more bytes follow: hand the parsed message to the
                # general loop's dispatch rules (pipelined burst)
                msgs = [] if proto.process_inline(msg, socket) \
                    else [(proto, msg)]
            else:
                msgs = []
        else:
            msgs = []
        while socket.input_portal:
            idx = socket.preferred_protocol
            if 0 <= idx < len(protocols):
                # burst fast path: a protocol already claimed this
                # connection and can batch-cut a pipelined window in one
                # native scan (tpu_std.batch_parse)
                bp = getattr(protocols[idx], "batch_parse", None)
                if bp is not None:
                    batch = bp(socket.input_portal, socket)
                    if batch:
                        proto = protocols[idx]
                        for msg in batch:
                            if not proto.process_inline(msg, socket):
                                msgs.append((proto, msg))
                        continue
            order = range(len(protocols)) if idx < 0 else (
                [idx] + [i for i in range(len(protocols)) if i != idx])
            claimed = None
            waiting_for_bytes = False
            ambiguous = False
            for i in order:
                proto = protocols[i]
                # parse contract: peek-only unless returning PARSE_OK
                status, msg = proto.parse(socket.input_portal, socket)
                if status == PARSE_OK:
                    socket.preferred_protocol = i
                    claimed = (proto, msg)
                    break
                if status == PARSE_NOT_ENOUGH_DATA:
                    # these bytes are this protocol's, just incomplete:
                    # stop and wait for more input
                    waiting_for_bytes = True
                    break
                # PARSE_TRY_OTHERS: not this protocol's bytes — but a
                # disclaim on a prefix shorter than the protocol's
                # discriminator is only tentative (segmented frame)
                if socket.input_portal.size < proto.min_probe_bytes:
                    ambiguous = True
            if claimed is not None:
                proto, msg = claimed
                # order-critical messages (stream frames) dispatch inline
                # in parse order; everything else may fan out to fibers
                if not proto.process_inline(msg, socket):
                    msgs.append(claimed)
                continue
            if not waiting_for_bytes and not ambiguous and \
                    socket.input_portal:
                # every protocol definitively disclaimed: drop the
                # connection (ambiguous short prefixes wait for more bytes)
                socket.set_failed(ValueError("unparsable input"))
            break
        if not msgs:
            return None
        record_dispatch_batch(len(msgs))
        if len(msgs) > 1:
            # bounded run-to-completion for the burst: RESPONSE
            # messages (no user handler — pure completion work) process
            # right here in parse order up to the inline budget, paying
            # zero wakes; requests and past-budget messages keep the
            # classic fresh-fiber fan-out (a blocking sync handler must
            # not serialize the burst), now spilled through ONE
            # amortized parking-lot signal (spawn_many) instead of a
            # signal per message.
            budget = flag("dispatch_inline_budget")
            inline_run = []
            spill = []
            for proto, msg in msgs[:-1]:
                meta = getattr(msg, "meta", None)
                if (len(inline_run) < budget and meta is not None
                        and hasattr(meta, "HasField")
                        and not meta.HasField("request")):
                    inline_run.append((proto, msg))
                else:
                    spill.append((proto, msg))
            if spill:
                counted_spawn_many(
                    self._control, socket,
                    [(lambda p=p_, m=m_: p.process(m, socket))
                     for p_, m_ in spill], name="process_burst")
            for proto, msg in inline_run:
                counted_run_inline(
                    self._control, socket,
                    (lambda p=proto, m=msg: p.process(m, socket)),
                    name=f"process_{proto.name}")
        proto, msg = msgs[-1]
        r = proto.process(msg, socket)
        if hasattr(r, "__await__"):
            return r
        return None


def process_in_parse_order(socket: Socket, key: str, item,
                           handler: Callable) -> None:
    """Serialize order-critical message handling per connection: append to
    a per-socket queue and let exactly one drain fiber run ``handler(item,
    socket)`` for each item in parse order. Fibers run on multiple OS
    threads, so the pending/draining handoff takes a real lock. Used by
    HTTP/1.1 pipelining and the RESP FIFO (any protocol whose responses
    must leave in request order)."""
    lock = socket.user_data.setdefault(key + "_lock", threading.Lock())
    with lock:
        pending = socket.user_data.setdefault(key + "_pending", deque())
        pending.append(item)
        if socket.user_data.get(key + "_draining"):
            return
        socket.user_data[key + "_draining"] = True

    async def _drain():
        while True:
            # popleft outside the flag check would race a new enqueue;
            # keep both under one lock acquisition
            with lock:
                if not pending:
                    socket.user_data[key + "_draining"] = False
                    return
                it = pending.popleft()
            try:
                await handler(it, socket)
            except BaseException as e:
                # a dead drain fiber with _draining still True would wedge
                # the connection forever: fail it so the peer sees a close
                # instead of a silent hang
                with lock:
                    socket.user_data[key + "_draining"] = False
                socket.set_failed(e if isinstance(e, Exception)
                                  else ConnectionError(f"drain died: {e!r}"))
                raise

    socket._control.spawn(_drain, name=key + "_serial")
