"""Ring lane: the batched-syscall event dispatcher (io_uring-style).

The fork's headline transport addition (src/bthread/ring_listener.*,
PAPER.md §layer 3) re-expressed for this stack: instead of a selector
tick that fires one Python callback per ready fd — each callback then
paying its own recv/send Python→libc round trip with a GIL
release/reacquire — the RingDispatcher tick is ONE GIL-released native
call (native/src/ring.cc) that polls the interest set AND executes the
whole ready-set's I/O: recv bursts, accept loops, one-shot writability.
Python drains the returned completion ring in bulk, and every response
written while draining is deferred onto a flush list that leaves as a
second single native call — a pipelined burst's responses depart as one
gather writev per connection instead of one send per RPC.

Selection is per-dispatcher: ``global_dispatcher()`` builds a
RingDispatcher when the ``event_ring_lane`` flag is on (env:
``BRPC_TPU_FLAG_EVENT_RING_LANE=1``) and the native extension is
available; the selector EventDispatcher stays the fallback lane and the
default. Conns that cannot hand their fd to the ring (ssl above-fd
buffering, chaos-wrapped conns whose write side must cross the fault
script) register poll-only: the ring reports readiness and their
classic callbacks run unchanged, so the chaos lane keeps observing
every byte it injects.

Completion-drain discipline (the graftlint-enforced contract, same as
the selector lane's event callbacks): everything this module runs on
the ring thread must be cheap — schedule fibers, feed portals, never
block. The scan lane's judge-or-defer posture carries over wholesale
because completions enter the SAME Socket machinery
(``Socket.ring_input`` → the classic parse/dispatch cycle).
"""

from __future__ import annotations

import errno
import logging
import os
import socket as pysocket
import threading
import time
from typing import Dict, Optional

from brpc_tpu.butil.flags import define_flag
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.transport import event_dispatcher as _evd

define_flag("event_ring_lane", False,
            "route the global event dispatcher through the ring lane "
            "(batched-syscall submission/completion ticks, native "
            "ring.cc); off = the selector lane. Per-dispatcher: "
            "existing dispatchers keep their lane")

# completion ops (must match native/src/ring.cc)
OP_RECV = 0
OP_ACCEPT = 1
OP_WRITEV = 2
OP_WRITABLE = 3
OP_READABLE = 4

_KIND_DATA = 0
_KIND_ACCEPT = 1
_KIND_POLL = 2

# handler slots (one list per fd, the EventDispatcher idiom)
_H_READ = 0      # classic on_readable (poll-only delivery)
_H_WRITE = 1     # one-shot on_writable
_H_ARMED = 2
_H_ONESHOT = 3
_H_KIND = 4
_H_SINK = 5      # ring_recv(data, eof, err) | ring_accept(fd_or_negerrno)

# ring-lane health at /vars: ticks, completion volume, and how much the
# write half batches (flushed_frames / flush_batches = frames per
# gather — the syscalls the lane removed vs one-send-per-frame)
nticks = Adder().expose("ring_ticks")
ncompletions = Adder().expose("ring_completions")
nflush_batches = Adder().expose("ring_flush_batches")
nflush_frames = Adder().expose("ring_flushed_frames")

# Current in-tick dispatcher for THIS thread: Socket._submit consults it
# (via try_defer_write) to route response frames into the end-of-tick
# flush instead of paying an inline send per frame. Only the ring
# thread ever sees a non-None value.
_tick_local = threading.local()


def try_defer_write(sock) -> bool:
    """True when ``sock``'s queued frames were handed to the current
    ring tick's write flush (the caller just claimed writership via its
    MPSC push; the flush settles it). False = no ring tick on this
    thread — the caller writes inline as usual."""
    d = getattr(_tick_local, "disp", None)
    if d is None:
        return False
    return d._defer_write(sock)


def ring_available() -> bool:
    from brpc_tpu.native import fastcore
    fc = fastcore.get()
    return fc is not None and hasattr(fc, "Ring")


class RingDispatcher:
    """EventDispatcher-compatible readiness engine over a native Ring.

    The public surface (add_consumer / pause_read / resume_read /
    request_writable / remove_consumer / stop) matches the selector
    dispatcher so conns wire up unchanged; data conns additionally pass
    ``ring_recv=`` (bytes flow natively) and listeners ``ring_accept=``
    (accepted fds arrive pre-made)."""

    ring_native = True

    def __init__(self, name: str = "ring_dispatcher"):
        from brpc_tpu.native import fastcore
        fc = fastcore.get()
        if fc is None or not hasattr(fc, "Ring"):
            raise RuntimeError("ring lane needs the fastcore extension")
        self._ring = fc.Ring()
        self.backend = self._ring.backend_name()
        self._lock = threading.Lock()
        self._barrier_cv = threading.Condition(self._lock)
        self._handlers: Dict[int, list] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._name = name
        # tick-barrier state: _tick_busy spans wait()+drain+flush;
        # consumers that must not overlap an in-flight native pass
        # (pluck claims, fd closes) kick the wakeup pipe and wait for
        # the CURRENT tick to settle (read_barrier)
        self._tick_busy = False
        self._tick_gen = 0
        # fds removed mid-tick: later completions of the SAME tick may
        # still name them (or a recycled fd number) — skip those
        self._tick_dead: set = set()
        # sockets whose writes this tick deferred (flush at tick end)
        self._flush: list = []
        # uring deferred gather writes awaiting their OP_WRITEV
        # completion: fd -> (socket, views, marks, total)
        self._pending_writes: Dict[int, tuple] = {}
        # stall-watchdog surface (flight recorder reads these off the
        # global dispatcher regardless of lane)
        self._tick_start_ns = 0
        self._tick_seq = 0
        self._wakeup_r, self._wakeup_w = pysocket.socketpair()
        self._wakeup_r.setblocking(False)
        wfd = self._wakeup_r.fileno()
        self._handlers[wfd] = [self._drain_wakeup, None, True, False,
                               _KIND_POLL, None]
        self._ring.register_fd(wfd, _KIND_POLL)

    # ------------------------------------------------------ registration
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run,
                                            name=self._name, daemon=True)
            self._thread.start()

    def _wakeup(self):
        if threading.current_thread() is self._thread:
            return
        try:
            self._wakeup_w.send(b"x")
        except (BlockingIOError, OSError):
            pass

    def _drain_wakeup(self):
        try:
            while self._wakeup_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def add_consumer(self, fd: int, on_readable, oneshot_read: bool = False,
                     ring_recv=None, ring_accept=None) -> None:
        """Register read interest. ``ring_recv(data, eof, err)`` makes
        the fd ring-native (the tick recvs it and delivers bytes);
        ``ring_accept(fd_or_negerrno)`` marks a listener. Neither =
        poll-only: readiness fires the classic ``on_readable``."""
        if ring_recv is not None:
            kind, sink = _KIND_DATA, ring_recv
        elif ring_accept is not None:
            kind, sink = _KIND_ACCEPT, ring_accept
        else:
            kind, sink = _KIND_POLL, None
        with self._lock:
            self._handlers[fd] = [on_readable, None, True, oneshot_read,
                                  kind, sink]
            self._ring.register_fd(fd, kind)
            self._ensure_thread()
        self._wakeup()

    def pause_read(self, fd: int) -> None:
        with self._lock:
            h = self._handlers.get(fd)
            if h is None or not h[_H_ARMED]:
                return
            h[_H_ARMED] = False
            self._ring.set_read(fd, False)
        # no wakeup: an in-flight tick may still observe the fd once —
        # consumers that need a hard cutoff follow with read_barrier()

    def resume_read(self, fd: int) -> None:
        with self._lock:
            h = self._handlers.get(fd)
            if h is None or h[_H_ARMED]:
                return
            h[_H_ARMED] = True
            self._ring.set_read(fd, True)
        # the in-flight native pass snapshotted its interest set at
        # entry: kick it so pending bytes are seen now, not at the next
        # 500ms boundary
        self._wakeup()

    def request_writable(self, fd: int, on_writable) -> None:
        with self._lock:
            h = self._handlers.get(fd)
            if h is None:
                self._handlers[fd] = [None, on_writable, False, False,
                                      _KIND_POLL, None]
                self._ring.register_fd(fd, _KIND_POLL)
                self._ring.set_read(fd, False)   # write interest only
            else:
                h[_H_WRITE] = on_writable
            self._ring.request_writable(fd)
            self._ensure_thread()
        self._wakeup()

    def remove_consumer(self, fd: int) -> None:
        with self._lock:
            self._handlers.pop(fd, None)
            self._ring.unregister_fd(fd)
            self._tick_dead.add(fd)
            # graftlint: disable=guarded-by -- _pending_writes is
            # ring-thread owned (defer/settle on the tick); this one
            # teardown pop from another thread holds _lock while the
            # native generation guard stales any in-flight CQE for fd.
            pend = self._pending_writes.pop(fd, None)
        if pend is not None:
            # a deferred uring gather was still in flight: its CQE is
            # now stale (suppressed by the native generation guard) —
            # settle the parked frames here so their done callbacks
            # fire with the failure instead of hanging to the deadline.
            # Outside the lock: settle fires user callbacks.
            sock, views, marks, total = pend
            sock.ring_settle_write(0, errno.EPIPE, views, marks, total)
        self._wakeup()
        # the caller closes the fd next (TcpConn.close): an in-flight
        # native pass still holding it in its poll/recv set would then
        # race a recycled fd NUMBER — wait the tick out (microseconds
        # once kicked; skipped on the ring thread itself, where being
        # in Python IS proof the native pass isn't running)
        self.read_barrier()

    def read_barrier(self) -> None:
        """Block until the in-flight tick (native pass + completion
        drain + write flush) settles. The pluck lane calls this after
        pausing read interest and BEFORE sending its request: past the
        barrier, the ring can no longer consume response bytes the
        plucker is about to read itself."""
        if threading.current_thread() is self._thread:
            return
        self._wakeup()
        with self._lock:
            gen = self._tick_gen
            while self._tick_busy and self._tick_gen == gen:
                self._barrier_cv.wait(0.05)

    # ------------------------------------------------------- write flush
    def _defer_write(self, sock) -> bool:
        # ring-thread only (the thread-local gate in try_defer_write);
        # the socket's push already claimed writership, which the tick
        # flush now owns until settle
        # graftlint: disable=guarded-by -- _flush is ring-thread
        # confined: the thread-local gate admits only the tick thread,
        # a single writer that needs no lock.
        self._flush.append(sock)
        return True

    def _flush_writes(self) -> None:
        socks, self._flush = self._flush, []
        batch = []
        metas = []
        for sock in socks:
            try:
                if sock.failed:
                    # fail-drain + retire through the classic writer
                    # (its failed branch fires every callback with the
                    # reason)
                    sock._drain_writes_inline()
                    continue
                views, marks, total = sock.ring_collect_writes()
                if not marks:
                    sock._drain_writes_inline()   # raced empty: retire
                    continue
                fd = -1
                pfd = getattr(sock.conn, "pluck_fd", None)
                if pfd is not None:
                    try:
                        fd = pfd()
                    except OSError:
                        fd = -1
                if fd < 0:
                    # no usable fd (failed mid-tick): park everything
                    # via the classic handoff — its writable
                    # continuation (or set_failed's cleanup) settles
                    # the frames
                    sock.ring_settle_write(0, 0, views, marks, total)
                    continue
                batch.append((fd, views))
                metas.append((sock, views, marks, total))
            except Exception:
                # one socket must not strand the rest of the round: an
                # escaping collect/settle (MemoryError, a broken conn
                # attr) fails THIS conn — set_failed + the classic
                # fail-drain retire everything still queued with the
                # reason — and the loop moves on, so the remaining
                # sockets' claimed writership still flushes
                logging.getLogger("brpc_tpu.transport").exception(
                    "ring flush collect failed; failing the conn")
                try:
                    sock.set_failed(
                        ConnectionError("ring flush collect failed"))
                    sock._drain_writes_inline()
                except Exception:
                    pass
        if not batch:
            return
        nflush_batches.add(len(batch))
        nflush_frames.add(sum(len(m[2]) for m in metas))
        try:
            results = self._ring.flush_writes(batch)
        except Exception:
            logging.getLogger("brpc_tpu.transport").exception(
                "ring write flush failed; parking batches")
            for sock, views, marks, total in metas:
                sock.ring_settle_write(0, 0, views, marks, total)
            return
        for (sock, views, marks, total), (fd, res, err) in zip(metas,
                                                               results):
            try:
                if res < 0 and err == 0:
                    # uring pending marker: the OP_WRITEV completion
                    # settles
                    self._pending_writes[fd] = (sock, views, marks,
                                                total)
                    continue
                sock.ring_settle_write(res, err, views, marks, total)
            except Exception:
                # same containment as the collect half: a raising
                # settle fails its own conn, the rest of the batch
                # still settles
                logging.getLogger("brpc_tpu.transport").exception(
                    "ring write settle failed; failing the conn")
                try:
                    sock.set_failed(
                        ConnectionError("ring write settle failed"))
                    sock._drain_writes_inline()
                except Exception:
                    pass

    # ---------------------------------------------------------- the loop
    def _run(self):
        _tick_local.disp = self
        log = logging.getLogger("brpc_tpu.transport")
        while not self._stop:
            with self._lock:
                self._tick_busy = True
                self._tick_dead.clear()
            try:
                try:
                    comps = self._ring.wait(500)
                except OSError:
                    continue
                except ValueError:      # ring closed under us (postfork)
                    return
                if not comps:
                    continue
                nticks.add(1)
                ncompletions.add(len(comps))
                self._tick_seq += 1
                self._tick_start_ns = time.monotonic_ns()
                try:
                    for comp in comps:
                        try:
                            self._dispatch_completion(comp)
                        except Exception:
                            log.exception(
                                "ring completion failed for fd %d", comp[0])
                finally:
                    # flush settles callbacks that may defer MORE
                    # writes (a completed response re-issues a call):
                    # loop until drained, bounded — a pathological
                    # re-issue chain falls back to inline writes
                    rounds = 0
                    while self._flush and rounds < 8:
                        rounds += 1
                        try:
                            self._flush_writes()
                        except Exception:
                            log.exception("ring flush round failed")
                            break
                    for sock in self._flush:
                        try:
                            sock._drain_writes_inline()
                        except Exception:
                            log.exception("ring flush fallback failed")
                    self._flush = []
                    dur_ms = (time.monotonic_ns() -
                              self._tick_start_ns) / 1e6
                    self._tick_start_ns = 0
                    if dur_ms > 1.0:
                        _evd._tick_ms_max.update(dur_ms)
            finally:
                with self._lock:
                    self._tick_busy = False
                    self._tick_gen += 1
                    self._barrier_cv.notify_all()

    def _dispatch_completion(self, comp) -> None:
        fd, op, res, payload = comp
        if op == OP_WRITEV:
            # settle FIRST, dead or alive: the parked frames' done
            # callbacks must fire exactly like the classic writer's
            # fail-drain (a removed consumer's entry would otherwise
            # leak and hang any waiter on a write ack until its RPC
            # deadline; ring_settle_write routes a failed socket's
            # frames through its failure machinery)
            pend = self._pending_writes.pop(fd, None)
            if pend is not None:
                sock, views, marks, total = pend
                if res >= 0:
                    sock.ring_settle_write(res, 0, views, marks, total)
                else:
                    sock.ring_settle_write(0, -res, views, marks, total)
            return
        with self._lock:
            if fd in self._tick_dead:
                # removed mid-tick (possibly re-registered on a
                # recycled fd number): this completion describes the
                # OLD consumer — drop it
                if op == OP_ACCEPT and res >= 0:
                    os.close(res)        # never leak an accepted fd
                return
            h = self._handlers.get(fd)
            cb = None
            if h is not None:
                if op == OP_WRITABLE:
                    cb, h[_H_WRITE] = h[_H_WRITE], None
                    if h[_H_READ] is None and h[_H_SINK] is None:
                        # write-only registration fully consumed
                        del self._handlers[fd]
                        self._ring.unregister_fd(fd)
                elif op == OP_READABLE and h[_H_ONESHOT]:
                    # one-shot read semantics for poll-only conns (ssl):
                    # disarm until resume_read, like the selector lane
                    h[_H_ARMED] = False
                    self._ring.set_read(fd, False)
        if h is None:
            if op == OP_ACCEPT and res >= 0:
                os.close(res)
            return
        # callbacks run OUTSIDE the registry lock (they re-enter the
        # dispatcher: pause/resume, remove on failure)
        if op == OP_RECV:
            sink = h[_H_SINK]
            if sink is not None:
                sink(payload if res > 0 else None,
                     res == 0, -res if res < 0 else 0)
            elif h[_H_READ] is not None:
                h[_H_READ]()
        elif op == OP_ACCEPT:
            sink = h[_H_SINK]
            if sink is not None:
                sink(res)
            elif res >= 0:
                os.close(res)
        elif op == OP_WRITABLE:
            if cb is not None:
                cb()
        elif op == OP_READABLE:
            if h[_H_READ] is not None:
                h[_H_READ]()

    def stop(self):
        self._stop = True
        self._wakeup()

    def _postfork_abandon(self):
        """Fork hygiene (called by event_dispatcher's postfork reset on
        the CHILD's copy): the ring thread exists only in the parent;
        close the child's copies of the wakeup pair and the native ring
        (batch: frees; uring: unmaps the rings and closes the ring fd —
        close(2) never disturbs the parent's kernel object)."""
        self._stop = True
        for s in (self._wakeup_r, self._wakeup_w):
            try:
                s.close()
            except Exception:
                pass
        try:
            self._ring.close()
        except Exception:
            pass
