"""TCP transport: non-blocking sockets driven by the EventDispatcher.

The reference's epoll-ET Socket/Acceptor path (brpc/socket.cpp,
acceptor.cpp) reduced to its essentials: non-blocking connect with
deferred writability, accept loop on the dispatcher, TCP_NODELAY on by
default (RPC latency over Nagle throughput).
"""

from __future__ import annotations

import errno
import os
import socket as pysocket
import threading
from typing import Callable, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.transport.base import Conn, Listener, Transport
from brpc_tpu.transport.event_dispatcher import global_dispatcher
# conn-boundary syscall floor (ISSUE 15): every Python->libc socket
# crossing below stamps one of these — the selector lane's cost the
# ring lane exists to batch away, counted where it's paid
from brpc_tpu.transport.syscall_stats import (py_accept as _c_accept,
                                              py_recv as _c_recv,
                                              py_writev as _c_writev)

define_flag("acceptor_backoff_ms", 100,
            "pause accepting for this long after the accept loop hits "
            "fd exhaustion (EMFILE/ENFILE) — a level-triggered listener "
            "would otherwise spin the dispatcher at 100% while the "
            "process is out of descriptors",
            validator=lambda v: v > 0)

# accept-loop health: each pause is one fd-exhaustion incident the
# timer-driven resume absorbed instead of a dispatcher hot-loop
naccept_pauses = Adder().expose("acceptor_fd_exhausted_pauses")


class TcpConn(Conn):
    # first write attempt runs inline in the caller's context (the
    # reference writes once in place before handing leftovers to
    # KeepWrite, socket.cpp:1960-2050): a nonblocking send of a small
    # frame almost always completes immediately, and the inline path
    # saves two fiber wakeups per RPC round trip. Safe because
    # cut_into_writer absorbs EAGAIN (partial frames hand off to the
    # keep_write fiber with the writing flag held).
    inline_write_ok = True

    # ring lane (transport/ring_lane.py): Socket offers its completion
    # sink before start_events; registration decides there whether the
    # dispatcher tick owns this fd's recv (ring-native) or readiness
    # fires the classic callback. Plain TCP is the only ring-native
    # conn — ssl buffers decrypted bytes above the fd and chaos conns
    # must keep every byte crossing their fault script.
    supports_ring_sink = True
    ring_sink = None             # set per-instance by Socket
    ring_attached = False        # stamped by start_events
    ring_pluck_ok = True         # batch backend: sync plucks can fence

    def __init__(self, sock: pysocket.socket, local: EndPoint, remote: EndPoint):
        sock.setblocking(False)
        try:
            sock.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        except OSError:
            pass
        try:
            # bulk-transfer buffers: default rmem/wmem mean ~64-128KB per
            # recv wakeup on a 1MB payload — each extra chunk costs a
            # syscall plus block bookkeeping on the drain path. 2MB (two
            # 1MB frames in flight per direction) keeps the pipe full
            # across a writable-event wake gap; 4MB measured no better
            # and grows the cache working set
            sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_RCVBUF, 2 << 20)
            sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_SNDBUF, 2 << 20)
        except OSError:
            pass
        self._sock = sock
        self._local = local
        self._remote = remote
        self._closed = False

    def write(self, mv: memoryview) -> int:
        _c_writev.add(1)
        try:
            return self._sock.send(mv)
        except BlockingIOError:
            raise
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                raise BlockingIOError from e
            raise

    def writev(self, views) -> int:
        """Gather-send (sendmsg): one syscall for a whole ref chain —
        a chunked 1MB response is ~6 scattered blocks, and per-block
        send() syscalls were the server's dominant cost
        (iobuf.h:177 prepare_iovecs / writev discipline)."""
        _c_writev.add(1)
        try:
            return self._sock.sendmsg(views)
        except BlockingIOError:
            raise
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                raise BlockingIOError from e
            raise

    def read_into_v(self, views) -> int:
        """Scatter-read (recvmsg_into): fill several blocks per syscall
        when a burst is pending (iobuf.h:469's readv-into-many-blocks)."""
        _c_recv.add(1)
        try:
            return self._sock.recvmsg_into(views)[0]
        except BlockingIOError:
            raise
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                raise BlockingIOError from e
            raise

    def read_into(self, mv: memoryview) -> int:
        _c_recv.add(1)
        try:
            return self._sock.recv_into(mv)
        except BlockingIOError:
            raise
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                raise BlockingIOError from e
            raise

    # level-triggered events (see start_events): a short read implies the
    # kernel buffer is (almost certainly) empty, and if not, the level
    # trigger fires again — Socket._drain_readable may stop early
    # without the EAGAIN recv round trip. Pause/resume move the
    # read-interest syscalls from per-message to per-busy-period.
    level_triggered = True

    def pluck_fd(self) -> int:
        """fd for the sync-pluck lane (Socket.pluck_until): a joining
        thread may poll+drain this conn directly. Only plain TCP offers
        it — SSL buffers decrypted bytes above the fd (a poll would
        miss them) and mem/ici have no fd."""
        return self._sock.fileno()

    def peek_closed(self) -> bool:
        """Non-consuming liveness probe (MSG_PEEK): True only when the
        peer's FIN has arrived AND no data remains to deliver — pending
        bytes keep the connection alive until a drain sees them."""
        try:
            return self._sock.recv(1, pysocket.MSG_PEEK) == b""
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        global_dispatcher().remove_consumer(self._sock.fileno())
        try:
            self._sock.close()
        except OSError:
            pass

    def start_events(self, on_readable, on_writable) -> None:
        self._on_writable = on_writable
        d = global_dispatcher()
        sink = self.ring_sink
        if sink is not None and getattr(d, "ring_native", False):
            # ring-native: the dispatcher tick recvs this fd inside its
            # one GIL-released native pass and delivers bytes through
            # the sink (Socket.ring_input); the classic callback stays
            # registered for readiness the ring cannot consume
            d.add_consumer(self._sock.fileno(), on_readable,
                           oneshot_read=False, ring_recv=sink)
            self.ring_attached = True
            self.ring_pluck_ok = d.backend == "batch"
            return
        # LEVEL-triggered: with inline processing the drain runs on the
        # dispatcher thread itself, so by the time the callback returns
        # the kernel buffer is empty and the level trigger is silent —
        # zero read-interest syscalls on the common path. The consumer
        # pauses read interest explicitly for the rare busy period
        # (handler suspended with data still arriving), which is where
        # one-shot arming paid a disarm+rearm syscall PER MESSAGE.
        d.add_consumer(self._sock.fileno(), on_readable,
                       oneshot_read=False)

    def ring_read_barrier(self) -> None:
        """Fence the in-flight ring tick (Socket.pluck_claim): past the
        return, the native pass can no longer consume this fd."""
        rb = getattr(global_dispatcher(), "read_barrier", None)
        if rb is not None:
            rb()

    def pause_read_events(self) -> None:
        global_dispatcher().pause_read(self._sock.fileno())

    def resume_read_events(self) -> None:
        global_dispatcher().resume_read(self._sock.fileno())

    def request_writable_event(self) -> None:
        global_dispatcher().request_writable(self._sock.fileno(), self._on_writable)

    @property
    def local_endpoint(self):
        return self._local

    @property
    def remote_endpoint(self):
        return self._remote


class _TcpListener(Listener):
    def __init__(self, sock: pysocket.socket, ep: EndPoint,
                 on_new_conn: Callable[[Conn], None]):
        self._sock = sock
        self._ep = ep
        self._on_new_conn = on_new_conn
        self._stopped = False
        sock.setblocking(False)
        d = global_dispatcher()
        if getattr(d, "ring_native", False):
            # ring-native listener: the tick's accept burst runs in the
            # native pass; fds arrive pre-made (nonblocking, cloexec)
            d.add_consumer(sock.fileno(), self._on_acceptable,
                           ring_accept=self._on_ring_accept)
        else:
            d.add_consumer(sock.fileno(), self._on_acceptable)

    def _on_ring_accept(self, res: int) -> None:
        """Ring completion sink: one accepted fd (or -errno) per call.
        The fd is already nonblocking+cloexec — wrap and hand off."""
        if res < 0:
            if -res in (errno.EMFILE, errno.ENFILE, errno.ENOMEM):
                # same fd-exhaustion discipline as the classic loop: the
                # kernel backlog would re-fire every tick — pause accept
                # interest and let the timer resume it
                self._pause_accept()
            return
        if self._stopped:
            os.close(res)                # raced stop: never leak the fd
            return
        try:
            s = pysocket.socket(fileno=res)
        except OSError:
            os.close(res)
            return
        try:
            addr = s.getpeername()
        except OSError:
            try:
                s.close()                # peer already gone (RST in backlog)
            except OSError:
                pass
            return
        local = self._ep
        remote = str2endpoint(f"tcp://{addr[0]}:{addr[1]}")
        self._on_new_conn(TcpConn(s, local, remote))

    def _on_acceptable(self):
        # accept-until-EAGAIN (acceptor.cpp:253 OnNewConnectionsUntilEAGAIN)
        while True:
            _c_accept.add(1)
            try:
                s, addr = self._sock.accept()
            except BlockingIOError:
                return
            except OSError as e:
                if e.errno in (errno.EMFILE, errno.ENFILE, errno.ENOMEM):
                    # fd exhaustion: the pending connection stays in the
                    # kernel backlog, so this LEVEL-triggered fd would
                    # re-fire the instant we return — a hot loop pinning
                    # the dispatcher exactly when the process is
                    # resource-starved. Pause accept interest and let a
                    # timer resume it once some fds may have freed
                    # (acceptor.cpp's EMFILE backoff discipline).
                    self._pause_accept()
                return
            local = self._ep
            remote = str2endpoint(f"tcp://{addr[0]}:{addr[1]}")
            self._on_new_conn(TcpConn(s, local, remote))

    def _pause_accept(self) -> None:
        naccept_pauses.add(1)
        global_dispatcher().pause_read(self._sock.fileno())
        from brpc_tpu.fiber.timer import global_timer
        global_timer().schedule_after(
            flag("acceptor_backoff_ms") / 1e3, self._resume_accept)

    def _resume_accept(self) -> None:
        if self._stopped:
            return     # raced stop(): never re-arm a closed (reusable) fd
        # re-arming is enough: the listener is LEVEL-triggered, so a
        # still-pending backlog re-fires _on_acceptable on the
        # dispatcher thread at its next select — accepting here on the
        # timer thread would both race that fire and stall every queued
        # timer behind a potentially backlog-deep accept loop
        global_dispatcher().resume_read(self._sock.fileno())

    def stop(self) -> None:
        self._stopped = True
        global_dispatcher().remove_consumer(self._sock.fileno())
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def endpoint(self) -> EndPoint:
        return self._ep


class TcpTransport(Transport):
    scheme = "tcp"

    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        sock = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
        if ep.extra("reuse_port") in ("1", "true"):
            # shard-group serving (the reference's -reuse_port,
            # server.cpp StartInternal): N worker processes each bind
            # this port and the kernel spreads accepted connections
            # across their listeners. Must be set BEFORE bind, and
            # every member of the group must set it.
            sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEPORT, 1)
        sock.bind((ep.host or "127.0.0.1", ep.port))
        sock.listen(1024)
        host, port = sock.getsockname()[:2]
        bound = EndPoint("tcp", host, port, ep.extras)
        return _TcpListener(sock, bound, on_new_conn)

    def connect(self, ep: EndPoint) -> Conn:
        sock = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        # blocking connect here keeps bring-up simple; the Socket layer's
        # write queue already tolerates slow establishment (the reference
        # does non-blocking connect + epollout; our dispatcher supports it
        # via request_writable if this ever shows up in profiles)
        sock.settimeout(10.0)
        sock.connect((ep.host, ep.port))
        sock.settimeout(None)
        lh, lp = sock.getsockname()[:2]
        return TcpConn(sock, str2endpoint(f"tcp://{lh}:{lp}"), ep)
