"""Global SocketMap: process-wide client connection sharing
(src/brpc/socket_map.h:147).

Two Channels pointed at the same server with connection_type="single"
should multiplex ONE connection, not open two — the reference dedups
via a global map keyed (EndPoint, connection type, ssl settings); here
the ssl flavor lives in the endpoint scheme, so the key is
(endpoint string, protocol). Entries are refcounted: each Channel holds
a lease; the socket closes when the last lease is returned (SocketMap's
insert/remove pairing), and a failed socket is replaced transparently on
the next acquire.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint

Key = Tuple[str, str]


class _Entry:
    __slots__ = ("socket", "refs")

    def __init__(self, socket):
        self.socket = socket
        self.refs = 0


class SocketMap:
    def __init__(self):
        self._map: Dict[Key, _Entry] = {}
        self._lock = threading.Lock()

    @staticmethod
    def key(ep: EndPoint, protocol: str = "") -> Key:
        return (str(ep), protocol)

    def acquire(self, key: Key, make: Callable[[], object]):
        """Return a shared live socket for key, creating it (outside the
        lock) if absent or failed. Each acquire must be paired with one
        release."""
        with self._lock:
            e = self._map.get(key)
            if e is not None and not e.socket.failed:
                e.refs += 1
                return e.socket
        new = make()
        with self._lock:
            e = self._map.get(key)
            if e is not None and not e.socket.failed:
                # lost the race: keep the winner, discard ours
                e.refs += 1
                winner = e.socket
            else:
                self._map[key] = e = _Entry(new)
                e.refs = 1
                winner = None
        if winner is not None:
            new.set_failed(ConnectionError("duplicate connect discarded"))
            return winner
        return new

    def release(self, key: Key, socket) -> None:
        """Drop one lease; the socket closes when the last lease goes
        (and only if it is still the mapped one)."""
        close = False
        with self._lock:
            e = self._map.get(key)
            if e is None or e.socket is not socket:
                close = True          # stale lease: not shared anymore
            else:
                e.refs -= 1
                if e.refs <= 0:
                    del self._map[key]
                    close = True
        if close and not socket.failed:
            socket.set_failed(ConnectionError("socket map released"))

    def evict_failed(self, key: Key, socket) -> None:
        """Remove a failed socket's entry so the next acquire redials
        (callers still hold their leases; release() of a stale lease is
        a no-op close on an already-failed socket)."""
        with self._lock:
            e = self._map.get(key)
            if e is not None and e.socket is socket:
                del self._map[key]

    def size(self) -> int:
        with self._lock:
            return len(self._map)


_global: Optional[SocketMap] = None
_glock = threading.Lock()


def global_socket_map() -> SocketMap:
    global _global
    if _global is None:
        with _glock:
            if _global is None:
                _global = SocketMap()
    return _global


def _postfork_reset() -> None:
    """Fork hygiene: pooled client sockets in the map are duplicated
    fds whose event registrations live in the PARENT's dispatcher —
    reusing one from the child would write on a connection the parent
    still owns. Drop the map; post-fork channels redial privately."""
    global _global, _glock
    _global = None
    _glock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("transport.socket_map", _postfork_reset)
