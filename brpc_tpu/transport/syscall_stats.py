"""Syscall accounting floor: recv/writev/accept counted at the native
boundary, merged into the /vars ``syscalls_per_rpc`` derived key.

Two stamp sites, one per boundary kind (ISSUE 15 satellite — "not
strace"):

* **Native loops** (ring.cc ticks, fastcore's pluck_scan/serve_drain
  fd loops) bump process-wide C atomics at the actual recv/writev/
  accept/poll call sites; ``_brpc_fastcore.syscall_counts()`` reads
  them.
* **Python conns** (transport/tcp.py) bump the Adders below at the
  conn-method boundary — the Python→libc crossing the ring lane
  exists to batch away.

Both lanes stamp at the same altitude, so the bench's ring-vs-selector
``syscalls_per_rpc`` ratio is honest: the selector lane's native echo
loops count exactly like the ring lane's ticks.

The denominator (``rpc_messages``) is stamped by the two dispatch
authorities: ``input_messenger.record_dispatch_batch`` (classic +
turbo lanes, requests AND responses — a loopback process counts both
sides of each call) and ``Server.account_native_batch`` (frames the
all-C echo loops served without ever crossing the interpreter).
"""

from __future__ import annotations

from brpc_tpu.bvar.reducer import Adder, PassiveStatus

# Python-side conn-boundary counters (tcp.py stamps these)
py_recv = Adder()
py_writev = Adder()
py_accept = Adder()

# messages dispatched / natively served — syscalls_per_rpc's denominator
rpc_msgs = Adder()


def note_rpc_messages(n: int) -> None:
    rpc_msgs.add(n)


_native_fn = False      # unresolved; None = extension absent


def _native_counts():
    """(recv, send, accept, poll) from the native boundary, (0,0,0,0)
    when the extension is absent. Resolved once — a /vars scrape must
    never trigger a compile (the loader caches after first use, and
    any process doing socket I/O resolved it long before a scrape)."""
    global _native_fn
    fn = _native_fn
    if fn is False:
        from brpc_tpu.native import fastcore
        try:
            fc = fastcore.get()
        except RuntimeError:    # sanitize-mode mismatch guard raced
            return (0, 0, 0, 0)
        fn = _native_fn = (getattr(fc, "syscall_counts", None)
                           if fc is not None else None)
    if fn is None:
        return (0, 0, 0, 0)
    return fn()


def snapshot() -> dict:
    """Merged totals since process start — the bench lanes window-delta
    this around their measurement to derive per-RPC costs."""
    nrecv, nsend, naccept, npoll = _native_counts()
    return {
        "recv": nrecv + (py_recv.get_value() or 0),
        "writev": nsend + (py_writev.get_value() or 0),
        "accept": naccept + (py_accept.get_value() or 0),
        "poll": npoll,
        "rpc_msgs": rpc_msgs.get_value() or 0,
    }


def syscalls_per_rpc() -> float:
    """Cumulative (recv + writev + accept) per dispatched RPC message —
    the ring-lane gate's cost metric. Poll/epoll wakeups are excluded:
    they amortize over whole ticks and would reward busy-waiting."""
    s = snapshot()
    denom = s["rpc_msgs"]
    if not denom:
        return 0.0
    return round((s["recv"] + s["writev"] + s["accept"]) / denom, 3)


_recv_var = PassiveStatus(lambda: snapshot()["recv"])
_writev_var = PassiveStatus(lambda: snapshot()["writev"])
_accept_var = PassiveStatus(lambda: snapshot()["accept"])
_ratio_var = PassiveStatus(syscalls_per_rpc)


def expose_syscall_vars() -> None:
    """(Re-)expose the syscall-floor bvars — called at import and again
    from Server.start, surviving a test fixture's unexpose_all like the
    other transport counters."""
    _recv_var.expose("syscalls_recv")
    _writev_var.expose("syscalls_writev")
    _accept_var.expose("syscalls_accept")
    _ratio_var.expose("syscalls_per_rpc")


expose_syscall_vars()
