"""tpud:// — the cross-host device transport (the DCN slot of SURVEY
§2.8: where tpu:// is the in-pod ICI lane, tpud carries the same Socket
contract between HOSTS over TCP).

One TCP stream carries enveloped frames:
    frame := type:u8 len:u32be payload
    type 0  app bytes        (delivered to the Socket's input portal)
    type 1  device batch     (staged arrays: count + per-array header+data)
    type 2  hello            (json handshake: the RDMA-style GID/QPN
                              exchange — device ordinal, process index,
                              local device count)

Ordering on the single stream guarantees the lane batch a message refers
to is decoded before the message bytes reach the parser (the sender
writes lane-then-frame, exactly like the in-process tpu:// transport).
Received arrays are materialized with ``jax.device_put`` onto this
host's target device at take time."""

from __future__ import annotations

import json
import struct
import sys
import threading
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.transport.base import Conn, Listener, Transport
from brpc_tpu.transport.tcp import TcpConn, TcpTransport

_F_BYTES = 0
_F_DEVICE = 1
_F_HELLO = 2
_HDR = struct.Struct(">BI")
_MAX_FRAME = 256 << 20
_MAX_OUT = 64 << 20          # backpressure cap on the staged out-buffer


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _hello_payload(device_ordinal: Optional[int]) -> bytes:
    info = {"device": device_ordinal or 0}
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            info["process_index"] = jax.process_index()
            info["local_device_count"] = jax.local_device_count()
        except Exception:
            pass
    return json.dumps(info).encode()


def _encode_device_batch(arrays) -> bytes:
    parts = [struct.pack(">H", len(arrays))]
    for arr in arrays:
        host = np.asarray(arr)
        dt = str(host.dtype).encode()
        parts.append(struct.pack(">B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack(">B", host.ndim))
        parts.append(struct.pack(f">{host.ndim}q", *host.shape)
                     if host.ndim else b"")
        raw = host.tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _decode_device_batch(data: bytes) -> List[np.ndarray]:
    (count,) = struct.unpack_from(">H", data, 0)
    pos = 2
    out = []
    for _ in range(count):
        (dtlen,) = struct.unpack_from(">B", data, pos)
        pos += 1
        dtype = _np_dtype(data[pos:pos + dtlen].decode())
        pos += dtlen
        (rank,) = struct.unpack_from(">B", data, pos)
        pos += 1
        shape = struct.unpack_from(f">{rank}q", data, pos) if rank else ()
        pos += 8 * rank
        (nbytes,) = struct.unpack_from(">Q", data, pos)
        pos += 8
        arr = np.frombuffer(data[pos:pos + nbytes],
                            dtype=dtype).reshape(shape)
        pos += nbytes
        out.append(arr)
    return out


class TpudConn(Conn):
    supports_device_lane = True
    lane_kind = "staged-dcn"     # /device cell label (device_stats)

    def __init__(self, inner: TcpConn, local: EndPoint, remote: EndPoint,
                 device_ordinal: Optional[int]):
        self._inner = inner
        self._local = local
        self._remote = remote
        self._device_ordinal = device_ordinal
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()   # single-flight TCP pushes
        self._out = bytearray()            # staged enveloped output
        self._inbuf = bytearray()          # raw inbound, pre-envelope
        self._appbuf = bytearray()         # de-enveloped app bytes
        self._lane: Deque[List] = deque()
        self._closed_read = False
        self.peer_info: Optional[dict] = None
        self._send_frame(_F_HELLO, _hello_payload(device_ordinal))

    # ----------------------------------------------------------- outbound
    def _send_frame(self, ftype: int, payload: bytes) -> None:
        with self._lock:
            if len(self._out) > _MAX_OUT:
                raise BlockingIOError("tpud out-buffer full")
            self._out += _HDR.pack(ftype, len(payload))
            self._out += payload
        self._flush()

    def _flush(self) -> bool:
        """Push staged bytes into the TCP socket; True if fully drained.
        Single-flight: two concurrent flushers would snapshot and send
        the same prefix twice, corrupting the stream."""
        with self._flush_lock:
            while True:
                with self._lock:
                    if not self._out:
                        return True
                    chunk = bytes(self._out[:256 << 10])
                try:
                    n = self._inner.write(memoryview(chunk))
                except BlockingIOError:
                    self._inner.request_writable_event()
                    return False
                with self._lock:
                    del self._out[:n]

    def write(self, mv: memoryview) -> int:
        # accept the whole chunk into the envelope buffer (bounded by
        # _MAX_OUT); partial TCP writes must never split our framing
        data = bytes(mv)
        self._send_frame(_F_BYTES, data)
        return len(data)

    def write_device_payload(self, arrays) -> bool:
        self._send_frame(_F_DEVICE, _encode_device_batch(arrays))
        return True

    # ------------------------------------------------------------ inbound
    def _pump(self) -> None:
        """Drain the TCP socket and de-envelope complete frames."""
        buf = bytearray(256 << 10)
        while True:
            try:
                n = self._inner.read_into(memoryview(buf))
            except BlockingIOError:
                break
            if n == 0:
                self._closed_read = True
                break
            self._inbuf += buf[:n]
        while len(self._inbuf) >= _HDR.size:
            ftype, length = _HDR.unpack_from(self._inbuf, 0)
            if length > _MAX_FRAME:
                raise ConnectionError(f"tpud frame of {length}B exceeds max")
            if len(self._inbuf) < _HDR.size + length:
                break
            payload = bytes(self._inbuf[_HDR.size:_HDR.size + length])
            del self._inbuf[:_HDR.size + length]
            if ftype == _F_BYTES:
                self._appbuf += payload
            elif ftype == _F_DEVICE:
                self._lane.append(_decode_device_batch(payload))
            elif ftype == _F_HELLO:
                try:
                    self.peer_info = json.loads(payload.decode())
                except ValueError:
                    raise ConnectionError("tpud: bad hello")
            else:
                raise ConnectionError(f"tpud: unknown frame type {ftype}")

    def read_into(self, mv: memoryview) -> int:
        self._pump()
        if self._appbuf:
            n = min(len(mv), len(self._appbuf))
            mv[:n] = self._appbuf[:n]
            del self._appbuf[:n]
            return n
        if self._closed_read:
            return 0
        raise BlockingIOError

    def take_device_payload(self):
        # no TCP pump: the lane frame precedes its message's byte frames,
        # so the batch is already decoded by the time the parser asks for
        # it — and pumping from the parse path would consume the readable
        # edge while leaving de-enveloped bytes nobody ever processes
        if not self._lane:
            return None
        batch = self._lane.popleft()
        jax = sys.modules.get("jax")
        if jax is None:
            return batch                    # numpy-only consumer
        try:
            devs = jax.devices()
            target = devs[self._device_ordinal or 0] \
                if (self._device_ordinal or 0) < len(devs) else devs[0]
            return [jax.device_put(a, target) for a in batch]
        except Exception:
            return batch

    # ----------------------------------------------------------- plumbing
    def close(self) -> None:
        self._inner.close()

    def start_events(self, on_readable: Callable[[], None],
                     on_writable: Callable[[], None]) -> None:
        def writable():
            if self._flush():
                on_writable()

        self._on_writable_cb = writable
        self._inner.start_events(on_readable, writable)

    def request_writable_event(self) -> None:
        self._inner.request_writable_event()

    def resume_read_events(self) -> None:
        resume = getattr(self._inner, "resume_read_events", None)
        if resume is not None:
            resume()

    @property
    def local_endpoint(self):
        return self._local

    @property
    def remote_endpoint(self):
        return self._remote


class _TpudListener(Listener):
    def __init__(self, inner: Listener, ep: EndPoint):
        self._inner = inner
        self._ep = ep

    def stop(self) -> None:
        self._inner.stop()

    @property
    def endpoint(self) -> EndPoint:
        return self._ep


class TpudTransport(Transport):
    scheme = "tpud"

    def __init__(self):
        self._tcp = TcpTransport()

    @staticmethod
    def _ordinal(ep: EndPoint) -> Optional[int]:
        return ep.device or 0

    def listen(self, ep: EndPoint, on_new_conn) -> Listener:
        ordinal = self._ordinal(ep)
        tcp_ep = EndPoint("tcp", ep.host or "127.0.0.1", ep.port, ep.extras)
        ready = threading.Event()   # accepts can fire before `bound` is set

        def wrap(conn: TcpConn):
            ready.wait(5)
            on_new_conn(TpudConn(conn, bound, conn.remote_endpoint, ordinal))

        inner = self._tcp.listen(tcp_ep, wrap)
        bound = EndPoint("tpud", inner.endpoint.host, inner.endpoint.port,
                         ep.extras)
        ready.set()
        return _TpudListener(inner, bound)

    def connect(self, ep: EndPoint) -> Conn:
        tcp_ep = EndPoint("tcp", ep.host, ep.port, ep.extras)
        inner = self._tcp.connect(tcp_ep)
        return TpudConn(inner, inner.local_endpoint, ep, self._ordinal(ep))
