"""RpcMesh: the pod fabric as a named device mesh.

The reference addresses peers by EndPoint; inside a pod the natural
address space is mesh coordinates. RpcMesh wraps jax.sharding.Mesh with
the two axes the RPC combinators use:

  'replica' — interchangeable servers (SelectiveChannel's replica set;
              data-parallel axis)
  'shard'   — partitions of one logical service (PartitionChannel's
              shards; tensor/sequence-parallel axis)

Collectives ride ICI when the mesh axes are laid out along the physical
torus — jax.make_mesh picks that layout by default on TPU.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

REPLICA_AXIS = "replica"
SHARD_AXIS = "shard"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma=None):
    """jax.shard_map across jax versions: newer jax exports it at top
    level (``check_vma``); older builds keep it in jax.experimental
    under the ``check_rep`` spelling of the same knob."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as xsm
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return xsm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_rpc_mesh(n_replicas: Optional[int] = None,
                  n_shards: Optional[int] = None,
                  devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_replicas is None and n_shards is None:
        n_replicas, n_shards = 1, n
    elif n_replicas is None:
        n_replicas = n // n_shards
    elif n_shards is None:
        n_shards = n // n_replicas
    if n_replicas * n_shards != n:
        raise ValueError(
            f"{n_replicas}x{n_shards} mesh does not cover {n} devices")
    return jax.make_mesh((n_replicas, n_shards), (REPLICA_AXIS, SHARD_AXIS),
                         devices=devices)


def shard_spec(*names: Optional[str]) -> PartitionSpec:
    return PartitionSpec(*names)


def sharding(mesh: Mesh, *names: Optional[str]) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*names))


def endpoint_for_coords(mesh: Mesh, replica: int, shard: int):
    """Mesh coordinate -> the device at that coordinate (the 'address' a
    tpu:// endpoint's device= extra refers to)."""
    return mesh.devices[replica][shard]
