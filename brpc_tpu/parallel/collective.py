"""Collective lowering: the combo-channel shapes compiled onto the mesh.

SURVEY.md §2.8's table, realized. When a ParallelChannel's sub-targets are
the devices of one mesh, N point-to-point RPCs + a host merge is the wrong
program for a TPU pod — the same dataflow is ONE SPMD computation whose
fan-out/merge are XLA collectives riding ICI:

  ParallelChannel fan-out + merge  -> scatter_gather(): shard_map of the
      service fn over the 'shard' axis, merge lowered to psum/all_gather
  Sharded addressing (Partition)   -> the in_spec partitioning itself
  Replica selection (Selective)    -> 'replica' axis; replicated in_spec
  Fan-in reduce (allreduce bench)  -> all_reduce()

Everything here is jit-compiled once per shape and reused — the RPC-side
analogue of the reference registering protocols once at GlobalInitialize.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS, shard_map


_MERGES = ("sum", "mean", "max", "min", "concat", "stack", "none")


class CollectiveChannel:
    """The ParallelChannel of a device mesh.

    call(service_fn, request): request is sharded over the 'shard' axis,
    service_fn runs per shard, responses merge on-device. service_fn must
    be a jax-traceable function shard -> shard_response.
    """

    def __init__(self, mesh: Mesh, merge: str = "concat"):
        if merge not in _MERGES:
            raise ValueError(f"merge must be one of {_MERGES}")
        self.mesh = mesh
        self.merge = merge
        self._compiled: Dict[Any, Callable] = {}

    # ------------------------------------------------------------ lowering
    def _lower(self, service_fn: Callable, merge: str) -> Callable:
        mesh = self.mesh

        def per_shard(x):
            y = service_fn(x)
            if merge == "sum":
                return jax.lax.psum(y, SHARD_AXIS)
            if merge == "mean":
                return jax.lax.pmean(y, SHARD_AXIS)
            if merge == "max":
                return jax.lax.pmax(y, SHARD_AXIS)
            if merge == "min":
                return jax.lax.pmin(y, SHARD_AXIS)
            return y  # concat/stack/none: stitching via out_specs

        if merge in ("sum", "mean", "max", "min"):
            out_spec = P()              # merged result replicated
        elif merge == "none":
            out_spec = P(SHARD_AXIS)    # leave sharded (response stays put)
        else:                           # concat / stack
            out_spec = P(SHARD_AXIS)
        fn = shard_map(per_shard, mesh=mesh, in_specs=P(SHARD_AXIS),
                           out_specs=out_spec)
        return jax.jit(fn)

    def call(self, service_fn: Callable, request, merge: Optional[str] = None):
        """One fan-out/merge over the shard axis. ``request``'s leading dim
        is scattered across shards (it must divide by shard count)."""
        merge = merge or self.merge
        key = (id(service_fn), merge)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._lower(service_fn, merge)
            self._compiled[key] = fn
        return fn(request)

    # ------------------------------------------------- common collectives
    def all_reduce(self, x, op: str = "sum"):
        return self.call(lambda s: s, x, merge=op)

    def all_gather(self, x):
        """Every shard sees the full request (fan-out broadcast side)."""
        fn = jax.jit(shard_map(
            lambda s: jax.lax.all_gather(s, SHARD_AXIS, tiled=True),
            mesh=self.mesh, in_specs=P(SHARD_AXIS), out_specs=P(),
            check_vma=False))  # replication holds post-all_gather; not inferable
        return fn(x)

    def reduce_scatter(self, x):
        fn = jax.jit(shard_map(
            lambda s: jax.lax.psum_scatter(s, SHARD_AXIS, tiled=True),
            mesh=self.mesh, in_specs=P(None), out_specs=P(SHARD_AXIS)))
        return fn(x)

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[SHARD_AXIS]


def all_to_all_reshard(mesh: Mesh, x, concat_axis: int, split_axis: int):
    """Ulysses-style resharding: move the sharded dimension of ``x`` from
    ``split_axis`` to ``concat_axis`` with one all-to-all over 'shard' —
    e.g. [seq/N, heads] -> [seq, heads/N] for long-sequence attention.
    The all-to-all is the sequence-parallel workhorse (SURVEY.md §5
    long-context analog)."""

    def per_shard(s):
        return jax.lax.all_to_all(s, SHARD_AXIS, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    in_spec = [None] * x.ndim
    in_spec[concat_axis] = SHARD_AXIS
    out_spec = [None] * x.ndim
    out_spec[split_axis] = SHARD_AXIS
    fn = shard_map(per_shard, mesh=mesh, in_specs=P(*in_spec),
                       out_specs=P(*out_spec))
    return jax.jit(fn)(x)


def replicated_call(mesh: Mesh, service_fn: Callable, request):
    """SelectiveChannel's degenerate mesh form: every replica holds the
    full request; the caller reads any replica's response (they're
    identical — replica choice becomes a scheduling detail, not a data
    movement)."""
    fn = shard_map(service_fn, mesh=mesh, in_specs=P(), out_specs=P())
    return jax.jit(fn)(request)
