"""Multi-host bring-up: jax.distributed + pod-wide mesh + naming
(the NCCL/MPI-backend slot of SURVEY §2.8 — XLA collectives over
ICI/DCN are the data plane; this module is the control-plane bootstrap).

    from brpc_tpu.parallel.distributed import init_pod, pod_mesh

    init_pod(coordinator="10.0.0.1:8476", num_processes=4, process_id=i)
    mesh = pod_mesh(n_replicas=2)     # global devices, all hosts

Single-process (or already-initialized) environments skip the
jax.distributed call, so the same code runs on a laptop, one TPU host,
or a pod. ``pod_endpoints`` enumerates tpud:// endpoints for every
process so RPC channels can reach each host's server (pair with the
mesh:// naming scheme for in-host device addressing)."""

from __future__ import annotations

from typing import List, Optional

_initialized = False


def init_pod(coordinator: Optional[str] = None,
             num_processes: Optional[int] = None,
             process_id: Optional[int] = None) -> None:
    """Initialize jax.distributed once (no-op when single-process or
    when the TPU runtime auto-detects the pod: all args None)."""
    global _initialized
    if _initialized:
        return
    import jax
    try:
        if coordinator is None and num_processes is None:
            # TPU pods auto-populate from the runtime; on CPU/single
            # process this raises or is unnecessary — both fine to skip
            if jax.process_count() > 1:
                _initialized = True
                return
            try:
                jax.distributed.initialize()
            except Exception:
                pass
        else:
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=num_processes,
                                       process_id=process_id)
    except RuntimeError:
        pass          # already initialized
    _initialized = True


def pod_mesh(n_replicas: Optional[int] = None,
             n_shards: Optional[int] = None):
    """RpcMesh over ALL devices in the pod (jax.devices() is global
    after init_pod)."""
    import jax

    from brpc_tpu.parallel.mesh import make_rpc_mesh
    return make_rpc_mesh(n_replicas=n_replicas, n_shards=n_shards,
                         devices=jax.devices())


def pod_endpoints(base_port: int = 8750, scheme: str = "tpud",
                  hosts: Optional[List[str]] = None) -> List[str]:
    """One RPC endpoint per process: ``tpud://<host>:<base_port>``.
    Hosts default to process indices on localhost (single-host testing);
    pass the real host list in a pod (the coordinator knows it)."""
    import jax

    n = jax.process_count()
    if hosts is None:
        hosts = ["127.0.0.1"] * n
    if len(hosts) != n:
        raise ValueError(f"{len(hosts)} hosts for {n} processes")
    return [f"{scheme}://{host}:{base_port + (0 if len(set(hosts)) == n else i)}"
            for i, host in enumerate(hosts)]
