"""Ring streaming: StreamingRPC lowered onto the ICI ring.

SURVEY.md §2.8: "StreamingRPC over a ring of ICI links = ring-attention-
style neighbor exchange". The shapes here:

  ring_shift      — every shard hands its block to the next ring neighbor
                    (one ppermute = one credit-window'd stream frame)
  ring_allreduce  — the classic reduce-scatter + all-gather ring (2(N-1)
                    neighbor exchanges, bandwidth-optimal on a torus)
  ring_scan       — fori_loop of shifts with a per-step combine: the
                    blockwise consumer pattern ring attention uses (each
                    step consumes a neighbor block while the next is in
                    flight, compute/comm overlapped by XLA)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from brpc_tpu.parallel.mesh import SHARD_AXIS, shard_map


def _ring_perm(n: int, step: int = 1):
    return [(i, (i + step) % n) for i in range(n)]


def ring_shift(mesh: Mesh, x, step: int = 1):
    """Shift shard blocks around the ring by ``step`` positions."""
    n = mesh.shape[SHARD_AXIS]

    def per_shard(s):
        return jax.lax.ppermute(s, SHARD_AXIS, perm=_ring_perm(n, step))

    fn = shard_map(per_shard, mesh=mesh, in_specs=P(SHARD_AXIS),
                       out_specs=P(SHARD_AXIS))
    return jax.jit(fn)(x)


def ring_allreduce(mesh: Mesh, x):
    """Bandwidth-optimal allreduce built from ppermute hops (what XLA's
    psum lowers to on a ring; spelled out here as the streaming bench and
    as the template for custom fused versions)."""
    n = mesh.shape[SHARD_AXIS]
    perm = _ring_perm(n, 1)

    def per_shard(block):
        # block: this shard's [n, chunk] stack of chunks
        chunks = block  # [n, chunk]

        def rs_step(i, st):
            acc, send = st
            recv = jax.lax.ppermute(send, SHARD_AXIS, perm=perm)
            idx = jax.lax.axis_index(SHARD_AXIS)
            # chunk each rank accumulates at step i of reduce-scatter
            j = (idx - i - 1) % n
            acc = acc.at[j].add(recv[j])
            send = acc
            return acc, send

        acc, _ = jax.lax.fori_loop(0, n - 1, rs_step, (chunks, chunks))

        def ag_step(i, st):
            acc, send = st
            recv = jax.lax.ppermute(send, SHARD_AXIS, perm=perm)
            idx = jax.lax.axis_index(SHARD_AXIS)
            j = (idx - i) % n
            acc = acc.at[j].set(recv[j])
            send = acc
            return acc, send

        acc, _ = jax.lax.fori_loop(0, n - 1, ag_step, (acc, acc))
        return acc

    # check_vma off: the carry flips between replicated and ring-varying
    # across loop steps, which the static varying-axes checker can't type
    fn = shard_map(per_shard, mesh=mesh, in_specs=P(None),
                       out_specs=P(None), check_vma=False)
    # x: [n, chunk] replicated; result: allreduced [n, chunk] replicated
    return jax.jit(fn)(x)


def ring_scan(mesh: Mesh, x, combine: Callable, init=None):
    """Blockwise ring consumption: each shard starts with its own block
    and, over n steps, combines every other shard's block as it arrives
    from the ring neighbor — the ring-attention dataflow
    (combine(carry, block) -> carry)."""
    n = mesh.shape[SHARD_AXIS]
    perm = _ring_perm(n, 1)

    def per_shard(block):
        carry0 = combine(init, block) if init is not None else block

        def step(i, st):
            carry, inflight = st
            recv = jax.lax.ppermute(inflight, SHARD_AXIS, perm=perm)
            carry = combine(carry, recv)
            return carry, recv

        carry, _ = jax.lax.fori_loop(0, n - 1, step, (carry0, block))
        return carry

    fn = shard_map(per_shard, mesh=mesh, in_specs=P(SHARD_AXIS),
                       out_specs=P(SHARD_AXIS))
    return jax.jit(fn)(x)
