"""Collective lowering of RPC fan-out/streaming onto device meshes."""

from brpc_tpu.parallel.mesh import (
    REPLICA_AXIS, SHARD_AXIS, endpoint_for_coords, make_rpc_mesh, sharding,
    shard_spec,
)
from brpc_tpu.parallel.collective import (
    CollectiveChannel, all_to_all_reshard, replicated_call,
)
from brpc_tpu.parallel.ring import ring_allreduce, ring_scan, ring_shift

__all__ = [
    "REPLICA_AXIS", "SHARD_AXIS", "endpoint_for_coords", "make_rpc_mesh",
    "sharding", "shard_spec",
    "CollectiveChannel", "all_to_all_reshard", "replicated_call",
    "ring_allreduce", "ring_scan", "ring_shift",
]