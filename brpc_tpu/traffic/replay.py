"""Open-loop replay/press engine over a captured corpus.

OPEN loop: issue times come from a precomputed schedule (the recorded
inter-arrival profile scaled by a time-warp factor, a constant qps, or
a seeded Poisson process) and are never gated on completions — a
closed sync loop measures the CLIENT's round-trip, not the server
(the PR 5 qps_client lesson), and worse, it mercy-throttles exactly
when the server slows down, hiding the overload the replay exists to
reproduce. Completions land on done-callbacks; the engine tracks how
far behind schedule issuing ever fell (``behind_ms_max``) so a
client-bound replay is visible instead of silently lying.

One process is one GIL: the multi-process fan-out lives in
tools/rpc_replay.py / tools/rpc_press.py (each worker runs this engine
on a round-robin slice of the corpus; reports merge with
merge_reports — counts sum, latency samples pool, never averaged
percentiles).

Replayed requests preserve the recorded method, payload, attachment,
priority tag, and deadline: timeout_ms re-derives from the recorded
budget (scaled by ``timeout_scale``; warp does NOT rescale deadlines —
compressing arrival gaps changes offered load, not caller patience).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.traffic.corpus import CapturedRequest

_LAT_CAP = 1024          # pooled-percentile reservoir per class


class PaceSpec:
    """mode: 'recorded' (inter-arrival x 1/warp), 'qps', 'poisson'."""

    def __init__(self, mode: str = "recorded", warp: float = 1.0,
                 qps: float = 0.0, seed: int = 0):
        if mode not in ("recorded", "qps", "poisson"):
            raise ValueError(f"unknown pace mode {mode!r}")
        if mode == "recorded" and warp <= 0.0:
            raise ValueError("warp must be > 0")
        if mode in ("qps", "poisson") and qps <= 0.0:
            raise ValueError(f"{mode} pacing needs qps > 0")
        self.mode = mode
        self.warp = warp
        self.qps = qps
        self.seed = seed

    def schedule_s(self, records: List[CapturedRequest]) -> List[float]:
        """Issue offsets (seconds from replay start), one per record,
        non-decreasing. Recorded mode anchors at the first record's
        arrival stamp; records without stamps issue immediately."""
        n = len(records)
        if self.mode == "qps":
            return [i / self.qps for i in range(n)]
        if self.mode == "poisson":
            rng = random.Random(self.seed)
            t = 0.0
            out = []
            for _ in range(n):
                out.append(t)
                t += rng.expovariate(self.qps)
            return out
        t0 = records[0].arrival_mono_ns if records else 0
        return [max(0.0, (r.arrival_mono_ns - t0) / 1e9 / self.warp)
                for r in records]

    def to_dict(self) -> dict:
        return {"mode": self.mode, "warp": self.warp, "qps": self.qps,
                "seed": self.seed}


class _ClassStats:
    __slots__ = ("ok", "fail", "error_codes", "lat_ms", "_seen", "_rng")

    def __init__(self, seed: int = 0):
        self.ok = 0
        self.fail = 0
        self.error_codes: Dict[str, int] = {}
        self.lat_ms: List[float] = []
        self._seen = 0
        self._rng = random.Random(seed)

    def record(self, code: int, lat_ms: float) -> None:
        if code:
            self.fail += 1
            k = str(code)
            self.error_codes[k] = self.error_codes.get(k, 0) + 1
            return
        self.ok += 1
        # bounded reservoir (unbiased): pooled percentiles across
        # workers need SAMPLES, and an unbounded list is a leak on a
        # long replay
        self._seen += 1
        if len(self.lat_ms) < _LAT_CAP:
            self.lat_ms.append(lat_ms)
        else:
            j = self._rng.randrange(self._seen)
            if j < _LAT_CAP:
                self.lat_ms[j] = lat_ms

    def to_dict(self) -> dict:
        return {"ok": self.ok, "fail": self.fail,
                "error_codes": dict(self.error_codes),
                "lat_ms_samples": [round(v, 3) for v in self.lat_ms]}


def _pct(sorted_vals: List[float], ratio: float) -> Optional[float]:
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(ratio * len(sorted_vals)))]


def run_open_loop(records: List[CapturedRequest], address: str,
                  pace: PaceSpec, conns: int = 4,
                  timeout_scale: float = 1.0,
                  default_timeout_ms: float = 2000.0,
                  bucket_width_s: float = 0.0,
                  drain_s: float = 10.0,
                  channel_options=None, warm: bool = True) -> dict:
    """Replay ``records`` against ``address`` on ``conns`` private
    connections (round-robin), open-loop on ``pace``'s schedule.
    Returns the per-class report (merge-ready: counts + bounded
    latency samples + schedule/issue bucket arrays)."""
    from brpc_tpu.rpc import Channel, ChannelOptions
    from brpc_tpu.rpc.controller import Controller

    if not records:
        return {"records": 0, "issued": 0, "ok": 0, "fail": 0,
                "elapsed_s": 0.0, "fidelity_pct": None, "classes": {}}
    sched = pace.schedule_s(records)
    span = max(sched[-1], 1e-3)
    if bucket_width_s <= 0.0:
        # 10..200 buckets: fine enough to see the recorded qps shape,
        # coarse enough that scheduler jitter doesn't drown it
        bucket_width_s = max(span / 200.0, min(0.1, span / 10.0))
    nbuckets = int(span / bucket_width_s) + 2
    sched_hist = [0] * nbuckets
    for t in sched:
        sched_hist[min(nbuckets - 1, int(t / bucket_width_s))] += 1

    if channel_options is None:
        channel_options = ChannelOptions(share_connections=False,
                                         name="traffic_replay")
    chs = [Channel(address, channel_options) for _ in range(conns)]
    if warm:
        # first-call channel setup costs milliseconds (connect + socket
        # plumbing) and would smear the schedule's first buckets into a
        # false fidelity loss. Warm with a nonexistent method: the
        # ENOSERVICE round trip pays the whole setup without touching
        # any real handler (replay determinism asserts count handler
        # hits, so a real-method warm call would pollute them).
        for ch in chs:
            ch.call_sync("__traffic_warm__", "Ping", b"")
    lock = threading.Lock()
    classes: Dict[str, _ClassStats] = {}
    issue_hist = [0] * nbuckets
    inflight = [0]
    done_ev = threading.Event()
    issued = [0]
    behind_max = [0.0]
    issue_done = [False]

    def _class(rec: CapturedRequest) -> _ClassStats:
        key = f"{rec.method_key}|p{rec.priority}"
        cs = classes.get(key)
        if cs is None:
            cs = classes[key] = _ClassStats(seed=pace.seed + len(classes))
        return cs

    def _issue(rec: CapturedRequest, i: int) -> None:
        cntl = Controller()
        if rec.timeout_ms > 0:
            cntl.timeout_ms = rec.timeout_ms * timeout_scale
        else:
            cntl.timeout_ms = default_timeout_ms
        if rec.priority:
            cntl.request_priority = rec.priority
        if rec.attachment:
            att = IOBuf()
            att.append(rec.attachment)
            cntl.request_attachment = att
        cs = _class(rec)
        t_issue = time.perf_counter()

        def _done(c) -> None:
            lat_ms = (time.perf_counter() - t_issue) * 1e3
            with lock:
                cs.record(c.error_code if c.failed() else 0, lat_ms)
                inflight[0] -= 1
                last = inflight[0] <= 0 and issue_done[0]
            if last:
                done_ev.set()

        with lock:
            inflight[0] += 1
        try:
            chs[i % conns].call(rec.service, rec.method, rec.payload,
                                cntl=cntl, done=_done)
        except Exception as e:  # noqa: BLE001 - a dead conn is a result
            with lock:
                cs.record(-1, 0.0)
                cs.error_codes[f"issue:{type(e).__name__}"] = \
                    cs.error_codes.get(f"issue:{type(e).__name__}", 0) + 1
                inflight[0] -= 1

    t0 = time.perf_counter()
    for i, (rec, t_s) in enumerate(zip(records, sched)):
        now = time.perf_counter() - t0
        if t_s > now:
            time.sleep(t_s - now)
            now = time.perf_counter() - t0
        elif now - t_s > behind_max[0]:
            # behind schedule: the OPEN loop issues anyway (that burst
            # IS the offered load); the gap is the client-bound signal
            behind_max[0] = now - t_s
        issue_hist[min(nbuckets - 1, int(now / bucket_width_s))] += 1
        _issue(rec, i)
        issued[0] += 1
    with lock:
        issue_done[0] = True
        drained = inflight[0] <= 0
    if not drained:
        done_ev.wait(drain_s + default_timeout_ms / 1e3)
    elapsed = time.perf_counter() - t0
    for ch in chs:
        ch.close()

    report = _summarize(classes, sched_hist, issue_hist, bucket_width_s)
    report.update({
        "records": len(records), "issued": issued[0],
        "elapsed_s": round(elapsed, 3),
        "behind_ms_max": round(behind_max[0] * 1e3, 2),
        "undrained": max(0, inflight[0]),
        "pace": pace.to_dict(),
    })
    return report


def _summarize(classes: Dict[str, _ClassStats], sched_hist: List[int],
               issue_hist: List[int], bucket_width_s: float) -> dict:
    per_method: Dict[str, dict] = {}
    per_priority: Dict[str, dict] = {}
    cls_out = {}
    total_ok = total_fail = 0
    for key, cs in sorted(classes.items()):
        d = cs.to_dict()
        lat = sorted(cs.lat_ms)
        d["p50_ms"] = round(_pct(lat, 0.5), 3) if lat else None
        d["p99_ms"] = round(_pct(lat, 0.99), 3) if lat else None
        cls_out[key] = d
        total_ok += cs.ok
        total_fail += cs.fail
        mk, _, p = key.rpartition("|p")
        for table, tkey in ((per_method, mk), (per_priority, p)):
            t = table.setdefault(tkey, {"ok": 0, "fail": 0})
            t["ok"] += cs.ok
            t["fail"] += cs.fail
    return {
        "ok": total_ok, "fail": total_fail, "classes": cls_out,
        "per_method": per_method, "per_priority": per_priority,
        "bucket_width_s": round(bucket_width_s, 4),
        "sched_hist": sched_hist, "issue_hist": issue_hist,
        "fidelity_pct": fidelity_pct(sched_hist, issue_hist),
    }


def fidelity_pct(sched_hist: List[int],
                 issue_hist: List[int]) -> Optional[float]:
    """How faithfully the issue times tracked the schedule: histogram
    overlap, 100 x sum(min(scheduled_b, issued_b)) / total scheduled.
    100 = every bucket got exactly its scheduled share; a client that
    fell behind and burst later scores low even though counts match."""
    total = sum(sched_hist)
    if not total:
        return None
    n = max(len(sched_hist), len(issue_hist))
    s = sched_hist + [0] * (n - len(sched_hist))
    a = issue_hist + [0] * (n - len(issue_hist))
    return round(100.0 * sum(min(x, y) for x, y in zip(s, a)) / total, 2)


def merge_reports(reports: List[dict]) -> dict:
    """Merge per-worker open-loop reports: counters sum, class latency
    SAMPLES pool (percentiles recomputed, never averaged), bucket
    histograms sum element-wise, fidelity recomputed from the merged
    histograms. behind_ms_max takes the max."""
    reports = [r for r in reports if r and r.get("records")]
    if not reports:
        return {"records": 0, "issued": 0, "ok": 0, "fail": 0,
                "workers": 0, "fidelity_pct": None, "classes": {}}
    out: dict = {"workers": len(reports)}
    for k in ("records", "issued", "ok", "fail", "undrained"):
        out[k] = sum(r.get(k, 0) or 0 for r in reports)
    out["elapsed_s"] = round(max(r.get("elapsed_s", 0.0)
                                 for r in reports), 3)
    out["behind_ms_max"] = round(max(r.get("behind_ms_max", 0.0)
                                     for r in reports), 2)
    out["pace"] = reports[0].get("pace")

    classes: Dict[str, dict] = {}
    for r in reports:
        for key, d in (r.get("classes") or {}).items():
            m = classes.setdefault(key, {"ok": 0, "fail": 0,
                                         "error_codes": {},
                                         "lat_ms_samples": []})
            m["ok"] += d.get("ok", 0)
            m["fail"] += d.get("fail", 0)
            for ec, n in (d.get("error_codes") or {}).items():
                m["error_codes"][ec] = m["error_codes"].get(ec, 0) + n
            m["lat_ms_samples"].extend(
                d.get("lat_ms_samples") or ())
    per_method: Dict[str, dict] = {}
    per_priority: Dict[str, dict] = {}
    for key, m in classes.items():
        lat = sorted(m["lat_ms_samples"])
        m["p50_ms"] = round(_pct(lat, 0.5), 3) if lat else None
        m["p99_ms"] = round(_pct(lat, 0.99), 3) if lat else None
        del m["lat_ms_samples"]
        mk, _, p = key.rpartition("|p")
        for table, tkey in ((per_method, mk), (per_priority, p)):
            t = table.setdefault(tkey, {"ok": 0, "fail": 0})
            t["ok"] += m["ok"]
            t["fail"] += m["fail"]
    out["classes"] = dict(sorted(classes.items()))
    out["per_method"] = per_method
    out["per_priority"] = per_priority

    widths = {r.get("bucket_width_s") for r in reports}
    if len(widths) == 1 and None not in widths:
        n = max(len(r.get("sched_hist") or []) for r in reports)
        sched = [0] * n
        issued = [0] * n
        for r in reports:
            for i, v in enumerate(r.get("sched_hist") or []):
                sched[i] += v
            for i, v in enumerate(r.get("issue_hist") or []):
                issued[i] += v
        out["bucket_width_s"] = widths.pop()
        out["fidelity_pct"] = fidelity_pct(sched, issued)
    else:
        # workers paced on different bucket widths: fall back to the
        # worst single-worker fidelity rather than inventing alignment
        fids = [r.get("fidelity_pct") for r in reports
                if r.get("fidelity_pct") is not None]
        out["fidelity_pct"] = min(fids) if fids else None
    return out


# ------------------------------------------------------ synthetic press

def parse_mix(spec: str, cast=int) -> List[tuple]:
    """'64:0.8,4096:0.2' -> [(64, 0.8), (4096, 0.2)] (weights
    normalized by the sampler, not here)."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        v, _, w = part.partition(":")
        out.append((cast(v), float(w) if w else 1.0))
    return out


def synthesize_records(n: int, sizes: List[tuple], priorities: List[tuple],
                       qps: float, mode: str = "qps", seed: int = 0,
                       service: str = "Bench", method: str = "PyEcho",
                       timeout_ms: float = 0.0) -> List[CapturedRequest]:
    """A synthetic corpus for press mode: ``n`` requests whose sizes
    and priority tags draw from weighted mixes and whose arrival
    stamps follow the pacing mode — the same CapturedRequest shape the
    capture lane records, so press and replay share one engine and a
    synthetic corpus can be written to .brpccap and inspected with
    rpc_view like a recorded one."""
    rng = random.Random(seed)
    sizes = sizes or [(64, 1.0)]
    priorities = priorities or [(0, 1.0)]
    sw = [w for _, w in sizes]
    pw = [w for _, w in priorities]
    t = 0.0
    out = []
    for i in range(n):
        size = rng.choices([s for s, _ in sizes], weights=sw)[0]
        prio = rng.choices([p for p, _ in priorities], weights=pw)[0]
        out.append(CapturedRequest(
            method_key=f"{service}.{method}", service=service,
            method=method,
            payload=bytes([65 + (i + size) % 26]) * size,
            attachment=b"", arrival_mono_ns=int(t * 1e9),
            arrival_wall_ns=0, timeout_ms=timeout_ms, priority=prio,
            log_id=i + 1, status=0, latency_us=0.0))
        t += rng.expovariate(qps) if mode == "poisson" else 1.0 / qps
    return out
