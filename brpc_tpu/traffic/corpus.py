"""The .brpccap corpus format: captured RPC requests over recordio.

One corpus file is a stream of recordio records (butil/recordio.py —
length-prefixed, crc32c-checksummed, resync-on-corruption), each record
one captured request:

    meta  = compact JSON {k,s,n,t,w,o,p,l,e,u,ps}
    data  = payload bytes || attachment bytes   (meta["ps"] splits)

      k  method key ("Service.Method")   s/n  service / method name
      t  arrival monotonic ns            w    arrival wall-clock ns
      o  request timeout_ms (0 = none)   p    priority tag (0 = unset)
      l  log_id                          e    completion error code
      u  completion latency us           ps   payload size (split point)

A sidecar index (``<corpus>.idx``, JSON) makes the reader O(1) for
summaries and record counts; it is validated against the corpus file's
size and record count and silently rebuilt by scanning when missing,
stale, or corrupt — a torn tail (the capturing process died mid-write)
loses at most the final record, never the file.
"""

# graftlint: disable-file=guarded-by -- CorpusWriter/CorpusReader are
# single-owner by protocol: exactly one thread holds a writer at a time
# (the capture writer thread while recording, an offline tool
# otherwise), and Recorder publishes the handle under Recorder._lock —
# the receiving thread sees the lock's barrier, never a live peer.

from __future__ import annotations

import json
import os
from typing import Dict, Iterator, List, NamedTuple, Optional

from brpc_tpu.butil.recordio import RecordReader, RecordWriter

SUFFIX = ".brpccap"
INDEX_SUFFIX = ".idx"
_INDEX_VERSION = 1


class CapturedRequest(NamedTuple):
    method_key: str
    service: str
    method: str
    payload: bytes
    attachment: bytes
    arrival_mono_ns: int
    arrival_wall_ns: int
    timeout_ms: float          # 0.0 = no deadline recorded
    priority: int              # 0 = unset
    log_id: int
    status: int                # completion error code (0 = OK)
    latency_us: float


def encode_meta(rec: CapturedRequest) -> bytes:
    return json.dumps({
        "k": rec.method_key, "s": rec.service, "n": rec.method,
        "t": rec.arrival_mono_ns, "w": rec.arrival_wall_ns,
        "o": rec.timeout_ms, "p": rec.priority, "l": rec.log_id,
        "e": rec.status, "u": round(rec.latency_us, 1),
        "ps": len(rec.payload),
    }, separators=(",", ":")).encode()


def decode_record(meta: bytes, data: bytes) -> Optional[CapturedRequest]:
    try:
        m = json.loads(meta)
        ps = int(m["ps"])
        return CapturedRequest(
            method_key=m["k"], service=m.get("s", ""),
            method=m.get("n", ""), payload=data[:ps],
            attachment=data[ps:],
            arrival_mono_ns=int(m.get("t", 0)),
            arrival_wall_ns=int(m.get("w", 0)),
            timeout_ms=float(m.get("o", 0) or 0.0),
            priority=int(m.get("p", 0)), log_id=int(m.get("l", 0)),
            status=int(m.get("e", 0)),
            latency_us=float(m.get("u", 0.0)))
    except (ValueError, KeyError, TypeError):
        return None        # foreign/corrupt meta: skip, keep reading


class CorpusWriter:
    """Append captured requests to a .brpccap file, maintaining the
    sidecar index on close(). NOT thread-safe by itself — the capture
    recorder serializes all writes on its one writer thread."""

    # the varying half of the record meta; the (key, service, method)
    # prefix is cached per method — a full json.dumps per record was
    # a measurable slice of the capture writer's GIL share
    _META_TAIL = (b',"t":%d,"w":%d,"o":%.3f,"p":%d,"l":%d,"e":%d,'
                  b'"u":%.1f,"ps":%d}')

    def __init__(self, path: str):
        self.path = path
        # TRUNCATES: one writer owns a corpus file for its whole life
        # (capture names files per pid+seq, merge/save replace).
        # Appending to a pre-existing file would make close() write a
        # sidecar index whose counts cover only this session while its
        # file_size matches — a "valid" index that lies. 1MB buffer:
        # the capture writer appends thousands of small records per
        # second — the default 8KB buffer turned that into a write
        # syscall every ~30 records.
        self._f = open(path, "wb", buffering=1 << 20)
        self._w = RecordWriter(self._f)
        self.records = 0
        self.bytes = 0
        self._methods: Dict[str, int] = {}
        self._priorities: Dict[str, int] = {}
        self._prefixes: Dict[str, bytes] = {}
        self._first_mono = 0
        self._last_mono = 0

    def write(self, rec: CapturedRequest) -> int:
        return self.write_fields(
            rec.method_key, rec.service, rec.method, rec.payload,
            rec.attachment, rec.arrival_mono_ns, rec.arrival_wall_ns,
            rec.timeout_ms, rec.priority, rec.log_id, rec.status,
            rec.latency_us)

    def write_fields(self, method_key: str, service: str, method: str,
                     payload: bytes, attachment: bytes,
                     arrival_mono_ns: int, arrival_wall_ns: int,
                     timeout_ms: float, priority: int, log_id: int,
                     status: int, latency_us: float) -> int:
        """Returns bytes appended. payload/attachment go to disk as
        separate chunks (write_chunks) — no concat copy — and the
        JSON meta assembles from a cached per-method prefix + one
        bytes interpolation (wire-compatible with encode_meta)."""
        pfx = self._prefixes.get(method_key)
        if pfx is None:
            if not service:
                # capture hands "" so the DISPATCH path never pays the
                # two pb string reads per request: the key is always
                # "Service.Method" (service.py full_name), so the
                # split happens here, once per method
                service, _, method = method_key.rpartition(".")
                if not service:
                    service, method = method_key, ""
            pfx = ('{"k":%s,"s":%s,"n":%s' % (
                json.dumps(method_key), json.dumps(service),
                json.dumps(method))).encode()
            if len(self._prefixes) < 4096:
                self._prefixes[method_key] = pfx
        meta = pfx + self._META_TAIL % (
            arrival_mono_ns, arrival_wall_ns, timeout_ms, priority,
            log_id, status, latency_us, len(payload))
        n = self._w.write_chunks((payload, attachment), meta)
        self.records += 1
        self.bytes += n
        self._methods[method_key] = self._methods.get(method_key, 0) + 1
        p = str(priority)
        self._priorities[p] = self._priorities.get(p, 0) + 1
        if not self._first_mono:
            self._first_mono = arrival_mono_ns
        if arrival_mono_ns:
            self._last_mono = max(self._last_mono, arrival_mono_ns)
        return n

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        if self._f.closed:
            return
        self._f.flush()
        size = self._f.tell()
        self._f.close()
        # the index is advisory: a failure writing it must not lose the
        # corpus (the reader falls back to a scan)
        try:
            tmp = self.path + INDEX_SUFFIX + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "version": _INDEX_VERSION, "file_size": size,
                    "records": self.records, "methods": self._methods,
                    "priorities": self._priorities,
                    "first_mono_ns": self._first_mono,
                    "last_mono_ns": self._last_mono,
                }, f)
            os.replace(tmp, self.path + INDEX_SUFFIX)
        except OSError:
            pass


class CorpusReader:
    """Iterate a corpus file's valid records; resyncs past torn tails
    and corrupt spans (recordio semantics). ``skipped_bytes`` and
    ``bad_records`` report what degradation cost."""

    def __init__(self, path: str):
        self.path = path
        self.bad_records = 0
        self.skipped_bytes = 0

    def __iter__(self) -> Iterator[CapturedRequest]:
        with open(self.path, "rb") as f:
            rr = RecordReader(f)
            for meta, data in rr:
                rec = decode_record(meta, data)
                if rec is None:
                    self.bad_records += 1
                    continue
                yield rec
            self.skipped_bytes = rr.skipped_bytes

    def records(self) -> List[CapturedRequest]:
        return list(self)

    # ------------------------------------------------------------ index
    def index(self, rebuild: bool = False) -> dict:
        """The summary index: record count, per-method and per-priority
        counts, corpus time span. Served from the sidecar when it
        matches the corpus file byte-for-size; rebuilt by scanning
        otherwise (stale index after a torn tail, missing sidecar,
        corrupt JSON)."""
        try:
            size = os.stat(self.path).st_size
        except OSError:
            size = -1
        if not rebuild:
            try:
                with open(self.path + INDEX_SUFFIX,
                          encoding="utf-8") as f:
                    idx = json.load(f)
                if idx.get("version") == _INDEX_VERSION \
                        and idx.get("file_size") == size:
                    idx["source"] = "sidecar"
                    return idx
            except (OSError, ValueError):
                pass
        methods: Dict[str, int] = {}
        priorities: Dict[str, int] = {}
        n = 0
        first = last = 0
        for rec in self:
            n += 1
            methods[rec.method_key] = methods.get(rec.method_key, 0) + 1
            p = str(rec.priority)
            priorities[p] = priorities.get(p, 0) + 1
            if not first:
                first = rec.arrival_mono_ns
            if rec.arrival_mono_ns:
                last = max(last, rec.arrival_mono_ns)
        return {"version": _INDEX_VERSION, "file_size": size,
                "records": n, "methods": methods,
                "priorities": priorities, "first_mono_ns": first,
                "last_mono_ns": last, "source": "scan",
                "bad_records": self.bad_records,
                "skipped_bytes": self.skipped_bytes}


def corpus_files(path: str) -> List[str]:
    """Resolve a corpus argument: a file, or a directory holding
    .brpccap files (a capture dir; legacy rpc_dump jsonl files are the
    caller's business)."""
    if os.path.isdir(path):
        return sorted(os.path.join(path, n) for n in os.listdir(path)
                      if n.endswith(SUFFIX))
    return [path]


def read_corpus(path: str) -> List[CapturedRequest]:
    """All valid records across a file or capture directory, ordered
    by arrival monotonic time (per-shard files interleave by stamp —
    each shard's clock is the same machine's monotonic clock)."""
    out: List[CapturedRequest] = []
    for f in corpus_files(path):
        out.extend(CorpusReader(f))
    out.sort(key=lambda r: r.arrival_mono_ns)
    return out


def merge_corpora(paths: List[str], out_path: str) -> dict:
    """Merge shard corpus files into one, ordered by arrival stamp —
    the supervisor's /capture download builds the group-wide corpus
    this way. Returns the merged index."""
    recs: List[CapturedRequest] = []
    for p in paths:
        recs.extend(CorpusReader(p))
    recs.sort(key=lambda r: r.arrival_mono_ns)
    w = CorpusWriter(out_path)    # truncates: merge replaces
    try:
        for r in recs:
            w.write(r)
    finally:
        w.close()
    return CorpusReader(out_path).index()
