"""Traffic engine: production request capture, time-warped replay, and
mixed-priority press (the reference's layer-7 rpc_dump + rpc_replay +
rpc_press + rpc_view tool set, rebuilt as a first-class subsystem).

  capture.py — sampled production recorder hooked into both server
               dispatch lanes; bounded disk, rotation, postfork-safe
               per-shard files, runtime control via the /capture page
  corpus.py  — the indexed .brpccap recordio corpus format (reader
               tolerates torn tails; writer keeps a sidecar index)
  replay.py  — open-loop replay/press engine: recorded-interval x
               time-warp / constant-qps / Poisson pacing, recorded
               deadline + priority preservation, per-class reports

The thin CLIs live in tools/: rpc_press.py (synthetic press),
rpc_replay.py (corpus replay), rpc_view.py (corpus inspector).
"""

from brpc_tpu.traffic.corpus import (CapturedRequest, CorpusReader,
                                     CorpusWriter, merge_corpora)

__all__ = ["CapturedRequest", "CorpusReader", "CorpusWriter",
           "merge_corpora"]
