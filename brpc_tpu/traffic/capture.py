"""Production request capture: the sampled recorder behind the traffic
engine (the reference's rpc_dump sampler grown into a subsystem —
rpc_dump.h:50-95 — plus the disk/rotation/runtime-control machinery a
production recorder needs).

Dispatch-path contract (both lanes hook in, classic and turbo):

    rec = recorder.sample_request(method_key, service, method,
                                  payload, attachment, arrival_ns,
                                  timeout_ms, log_id, priority)
    ... handler runs ...                   # rec None = not sampled
    recorder.record_complete(rec, error_code, latency_us)

(Hook names are deliberately UNIQUE verbs — ``on_complete`` /
``enabled`` style names collide with stored-callback attributes and
module functions elsewhere in the tree, and the lock model's
unique-method fallback then mints false lock-graph edges onto this
class; the PR 10 ``on_failure`` lesson.)

``sample_request`` is the sampling decision (per-method rates over a
default rate, plus an optional per-second budget) and costs one dict
lookup + an RNG draw when sampling is fractional; the record rides the
request and is ENQUEUED at completion so it carries status + latency.
Disk writes happen on a dedicated writer thread — never on the
dispatch path, and never under the recorder lock (the lock guards the
queue and counters only; the blocking-under-lock rule pins this).

Files are per-pid (``capture-<pid>-<seq>.brpccap``) so a forked shard
records to its OWN file after the postfork reset; the shard supervisor
merges per-shard files for /capture downloads. Rotation bounds a
single file (``capture_rotate_mb``), the disk budget bounds the whole
capture dir (``capture_disk_budget_mb``) by deleting the oldest CLOSED
file.

Legacy aliases: the seed stub's ``rpc_dump_dir`` /
``rpc_dump_max_requests_per_second`` flags keep working — an active
``rpc_dump_dir`` auto-starts this recorder with the legacy budget (see
rpc/rpc_dump.py for the shim that keeps its old API alive on top).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from brpc_tpu.butil import postfork
from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.reducer import Adder, PassiveStatus
from brpc_tpu.traffic.corpus import (SUFFIX, CapturedRequest, CorpusReader,
                                     CorpusWriter)

define_flag("capture_dir", "", "directory for captured request corpora "
            "(empty = capture off unless started via /capture or the "
            "legacy rpc_dump_dir alias)")
define_flag("capture_sample_rate", 1.0,
            "default per-request sampling probability",
            validator=lambda v: 0.0 <= v <= 1.0)
define_flag("capture_method_rates", "",
            "per-method sampling overrides, 'Svc.M=0.1,Other.N=1.0'")
# default budget 2000/s: production capture is SAMPLED (the reference
# ships rpc_dump at 100/s) — the budget bounds the recorder's GIL
# share at ~0.5% regardless of server qps, while full capture
# (max_per_second=0, what corpus-recording sessions use) costs ~5-7%
# at 4k qps on this sandbox. The budget counter is deliberately
# lock-free and approximate — a sampler's budget tolerates ±a few
# records far better than the hot path tolerates a lock.
define_flag("capture_max_per_second", 2000,
            "global sampled-records-per-second budget (0 = unlimited)",
            validator=lambda v: v >= 0)
define_flag("capture_rotate_mb", 64,
            "rotate the active corpus file past this size",
            validator=lambda v: v >= 1)
define_flag("capture_disk_budget_mb", 256,
            "delete oldest closed corpus files past this total",
            validator=lambda v: v >= 1)

# /vars counters: what capture wrote and what it dropped must be
# observable without reading the page. Passive reads of the recorder's
# own counters — per-request Adder.add on the sampled path costs a
# thread-local agent lookup each call, and "sampled" is exactly
# written + dropped + pending anyway.
nwritten = Adder().expose("capture_written")
ndropped_queue = Adder().expose("capture_dropped_queue")
PassiveStatus(lambda: _recorder.dropped_budget).expose(
    "capture_dropped_budget")
PassiveStatus(
    lambda: _recorder.written + _recorder.dropped_queue
    + len(_recorder._q)).expose("capture_sampled")

# pending-record queue bounds: records queue at completion and drain
# on the writer's 100ms tick, so the bound only matters when the
# writer is GIL-starved behind a hot dispatch path — size it so a
# multi-second starvation absorbs without drops (records are cheap;
# the BYTE budget is the real memory guard for big payloads)
_QUEUE_CAP = 32768
_QUEUE_BYTES_CAP = 32 << 20
_WRITE_BATCH = 256         # records drained per writer-lock hold


class CaptureConfig:
    def __init__(self, dir: str, default_rate: float = 1.0,
                 method_rates: Optional[Dict[str, float]] = None,
                 max_per_second: int = 0, rotate_bytes: int = 64 << 20,
                 disk_budget_bytes: int = 256 << 20,
                 seed: Optional[int] = None):
        # normalized: the writer compares file dirnames against this
        # (a trailing slash would make every comparison miss and the
        # writer would rotate to a fresh file per drain tick)
        self.dir = os.path.normpath(dir) if dir else dir
        self.default_rate = default_rate
        self.method_rates = dict(method_rates or {})
        self.max_per_second = max_per_second
        self.rotate_bytes = rotate_bytes
        self.disk_budget_bytes = disk_budget_bytes
        self.seed = seed

    @classmethod
    def from_flags(cls, dir: Optional[str] = None, **overrides):
        rates: Dict[str, float] = {}
        for part in flag("capture_method_rates").split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            k, _, v = part.partition("=")
            try:
                rates[k.strip()] = max(0.0, min(1.0, float(v)))
            except ValueError:
                pass
        cfg = cls(dir if dir is not None else flag("capture_dir"),
                  default_rate=flag("capture_sample_rate"),
                  method_rates=rates,
                  max_per_second=flag("capture_max_per_second"),
                  rotate_bytes=flag("capture_rotate_mb") << 20,
                  disk_budget_bytes=flag("capture_disk_budget_mb") << 20)
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg

    def to_dict(self) -> dict:
        return {"dir": self.dir, "default_rate": self.default_rate,
                "method_rates": dict(self.method_rates),
                "max_per_second": self.max_per_second,
                "rotate_mb": self.rotate_bytes >> 20,
                "disk_budget_mb": self.disk_budget_bytes >> 20}


# the per-request carrier between sample_request and record_complete:
# PLAIN TUPLE — one cheap allocation on the sampled path:
#   (method_key, service, method, payload, attachment_bytes,
#    arrival_mono_ns, timeout_ms, log_id, priority)
# (wall-clock stamps are derived by the writer from the recorder's
# clock anchor; index names below for the writer side)
_K, _S, _N, _PAY, _ATT, _T, _O, _L, _P = range(9)


class Recorder:
    """Process-wide capture singleton (global_recorder()). The lock
    guards queue + counters + lifecycle state ONLY — file handles are
    touched exclusively by the writer thread, and the dispatch path
    never blocks on disk."""

    def __init__(self):
        self._lock = threading.Lock()
        self._q: deque = deque()
        self._q_bytes = 0
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cfg: Optional[CaptureConfig] = None
        self._active = False
        self._stopping = False
        self._rng = random.Random()
        self._second = 0
        self._taken = 0
        # (wall_ns, mono_ns) pair from start(): the writer derives
        # every record's wall stamp from it instead of the hot path
        # paying a time.time_ns() per sample
        self._clock_anchor = (time.time_ns(), time.monotonic_ns())
        self._legacy = False       # started via the rpc_dump_dir alias
        # incident-window state (incident/manager.py): while True the
        # recorder runs a corpus-recording session the anomaly watchdog
        # opened, and _pre_incident holds (cfg, active, legacy) from
        # before the window so end_incident_capture restores the
        # operator's session — not flag defaults
        self._incident_mode = False
        self._pre_incident: Optional[tuple] = None
        # writer-thread-only state (no lock needed: one owner)
        self._writer: Optional[CorpusWriter] = None
        self._file_seq = 0
        self._closed_files: List[str] = []
        # lifetime counters (the bvars read them passively; these
        # survive unexpose_all and feed the /capture page)
        self.written = 0
        self.written_bytes = 0
        self.dropped_queue = 0
        self.dropped_budget = 0
        self.rotations = 0
        self.deleted_files = 0

    # ----------------------------------------------------------- control
    def start(self, cfg: CaptureConfig, legacy: bool = False,
              _incident: bool = False) -> None:
        """Begin a capture SESSION: counters restart at zero (the
        /capture page reports this session, the corpus files report
        history), the clock anchor re-pins, sampling state resets."""
        os.makedirs(cfg.dir, exist_ok=True)
        # a previous session's writer may still be draining (a stop()
        # whose flush budget expired leaves it running — see stop):
        # settle it first, so exactly ONE writer ever owns the file
        # state. start() is control-plane; a short wait here is fine.
        with self._lock:
            t = self._thread if self._stopping else None
        if t is not None:
            self._wake.set()
            t.join(5.0)
        with self._lock:
            if self._thread is not None \
                    and not self._thread.is_alive():
                self._thread = None
                self._stopping = False
            if not _incident and self._incident_mode:
                # an operator reconfigure that lands MID-incident-window
                # wins: this config becomes the session truth and the
                # window's eventual end_incident_capture restore
                # dissolves into a no-op
                self._incident_mode = False
                self._pre_incident = None
            self._cfg = cfg
            self._legacy = legacy
            if cfg.seed is not None:
                self._rng.seed(cfg.seed)
            self._clock_anchor = (time.time_ns(), time.monotonic_ns())
            if not self._active:
                self.written = self.written_bytes = 0
                self.dropped_queue = 0
                # graftlint: disable=guarded-by -- dropped_budget is
                # approximate accounting: its dispatch-path bump in
                # sample_request is deliberately lock-free (a racy int,
                # observability-only), so no guard is inferrable; this
                # locked session reset only restarts the gauge.
                self.dropped_budget = 0
                self.rotations = self.deleted_files = 0
            self._active = True
            self._stopping = False
            self._ensure_thread_locked()

    def stop(self, flush_s: float = 5.0) -> None:
        """Stop sampling and flush the queue: pending records drain to
        disk, the active file closes (index written) so the corpus is
        immediately downloadable. If the writer cannot finish inside
        ``flush_s`` (stalled disk, flush_s=0 from a dispatch-path
        caller), the stopping state is LEFT IN PLACE — the writer
        exits on its own once drained, and the next start() settles
        it. Resetting the flags while the old thread still runs would
        let a restart spawn a SECOND writer over the same file
        state."""
        with self._lock:
            if not self._active and self._thread is None:
                return
            self._active = False
            self._stopping = True
            t = self._thread
        self._wake.set()
        if t is not None:
            t.join(flush_s)
            if t.is_alive():
                return
        with self._lock:
            self._thread = None
            self._stopping = False

    def begin_incident_capture(self, cfg: CaptureConfig) -> bool:
        """Enter corpus-recording mode for an anomaly's bounded window
        (incident/manager.py). Saves the live session state — config,
        active, legacy — so the window's close RESTORES it: an
        operator capturing at sampled rates before the incident is
        capturing at the same rates, budget and dir after it, not at
        flag defaults. Returns False when a window is already in
        progress (one incident records at a time) or the spool dir is
        unusable."""
        with self._lock:
            if self._incident_mode:
                return False
            self._pre_incident = (self._cfg, self._active, self._legacy)
            self._incident_mode = True
        try:
            self.start(cfg, _incident=True)
        except OSError:
            with self._lock:
                self._incident_mode = False
                self._pre_incident = None
            return False
        return True

    def end_incident_capture(self, flush_s: float = 3.0) -> bool:
        """Close the incident window: flush/stop the corpus-recording
        session, then restore whatever the operator had running before
        the window. Returns False when no window is active (including
        the operator-reconfigured-mid-window case, where the operator's
        session keeps running untouched)."""
        with self._lock:
            if not self._incident_mode:
                return False
            prior, self._pre_incident = self._pre_incident, None
            self._incident_mode = False
        self.stop(flush_s=flush_s)
        prior_cfg, was_active, was_legacy = prior or (None, False, False)
        if was_active and prior_cfg is not None:
            try:
                self.start(prior_cfg, legacy=was_legacy)
            except OSError:
                pass
        else:
            with self._lock:
                # idle before the window: leave idle, but point the
                # config surfaces (corpus_paths, /capture page) back at
                # the pre-window session instead of the deleted spool
                self._cfg = prior_cfg
                self._legacy = was_legacy
        return True

    def incident_capturing(self) -> bool:
        return self._incident_mode

    def capture_enabled(self) -> bool:
        """The dispatch-path gate: one attribute read when capture was
        never configured; the legacy rpc_dump_dir flag keeps working as
        an implicit starter (checked only while inactive)."""
        if self._active:
            return True
        d = _legacy_dir()
        if d:
            self._start_legacy(d)
            return self._active
        return False

    def capturing(self) -> bool:
        return self._active

    def _start_legacy(self, d: str) -> None:
        cfg = CaptureConfig.from_flags(dir=d)
        budget = _legacy_budget()
        if budget and not cfg.max_per_second:
            cfg.max_per_second = budget
        try:
            self.start(cfg, legacy=True)
        except OSError:
            with self._lock:
                self._active = False  # bad legacy dir: stay off

    # ---------------------------------------------------------- sampling
    def sample_request(self, method_key: str, service: str, method: str,
                   payload: bytes, attachment, arrival_ns: int,
                   timeout_ms: float = 0.0, log_id: int = 0,
                   priority: int = 0) -> Optional[tuple]:
        cfg = self._cfg
        if cfg is None or not self._active:
            return None
        if self._legacy and not _legacy_dir() and not flag("capture_dir"):
            # the legacy flag was cleared at runtime (the seed stub's
            # off switch): honor it
            self.stop(flush_s=0.0)
            return None
        rate = cfg.method_rates.get(method_key, cfg.default_rate)
        if rate <= 0.0:
            return None
        if rate < 1.0 and self._rng.random() >= rate:
            return None
        if cfg.max_per_second:
            # LOCK-FREE per-second budget: racing threads may reset the
            # window twice or lose a few increments — a sampling budget
            # is approximate by definition, and a lock here would sit
            # on every request of every dispatch thread
            now = int(time.monotonic())
            if now != self._second:
                self._second = now
                self._taken = 0
            if self._taken >= cfg.max_per_second:
                self.dropped_budget += 1   # racy int, observability-only
                return None
            self._taken += 1
        # attachment snapshot NOW: the handler/response path may alias
        # and consume the request buffers after completion (to_bytes is
        # identity — no copy — for the single-block common case)
        att = b""
        if attachment is not None:
            att = attachment if attachment.__class__ is bytes \
                else attachment.to_bytes()
        return (method_key, service, method, payload, att,
                arrival_ns, timeout_ms or 0.0, log_id, priority)

    def record_complete(self, rec: Optional[tuple], error_code: int,
                    latency_us: float) -> None:
        if rec is None:
            return
        nbytes = len(rec[_PAY]) + len(rec[_ATT])
        with self._lock:
            if not self._active:
                return
            depth = len(self._q)
            if depth >= _QUEUE_CAP or \
                    self._q_bytes + nbytes > _QUEUE_BYTES_CAP:
                self.dropped_queue += 1
                ndropped_queue.add(1)
                return
            self._q.append((rec, error_code, latency_us))
            self._q_bytes += nbytes
            if self._thread is None:
                # postfork left no writer; normal operation never
                # re-checks thread liveness per request
                self._ensure_thread_locked()
        if depth >= _QUEUE_CAP // 2 or \
                self._q_bytes > _QUEUE_BYTES_CAP // 2:
            # wake ELISION is the hot-path discipline: the writer polls
            # every 100ms and a per-enqueue Event.set() (futex) was the
            # single biggest capture cost under pipelined load. The
            # explicit wake exists only for backpressure (queue half
            # full — drain NOW, before the cap drops records) and for
            # stop()'s flush.
            self._wake.set()

    def _ensure_thread_locked(self) -> None:
        # caller holds self._lock
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._record_writer_loop, name="capture_writer",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------ writer thread
    def _record_writer_loop(self) -> None:
        """Drains the completed-record queue to the corpus file.
        Single owner of every file handle; queue pops under the lock,
        disk writes outside it. All imports are module-level — this is
        recorder-thread code (sampler-no-lazy-import rule)."""
        while True:
            self._wake.wait(0.1)
            self._wake.clear()
            # drain by SWAPPING the whole deque under one O(1) lock
            # hold: popping records one-by-one under the lock held it
            # for tens of microseconds per batch, and every request
            # completing on the dispatch side blocked behind it —
            # measured as the bigger half of the enqueue leg's cost
            with self._lock:
                batch, self._q = self._q, deque()
                self._q_bytes = 0
                stopping = self._stopping
            if batch:
                try:
                    self._write_batch(batch)
                except Exception:
                    # a full/broken disk (or any writer bug) must
                    # never take serving down; the records are lost,
                    # the counter says so
                    with self._lock:
                        self.dropped_queue += len(batch)
                    ndropped_queue.add(len(batch))
            if stopping:
                w, self._writer = self._writer, None
                if w is not None:
                    try:
                        w.close()
                        self._closed_files.append(w.path)
                    except OSError:
                        pass
                return

    def _write_batch(self, batch) -> None:
        with self._lock:
            # start() swaps cfg and the clock anchor under _lock on a
            # runtime reconfigure while this thread is still draining:
            # snapshot both together or the wall stamps mix anchors
            cfg = self._cfg
            wall0, mono0 = self._clock_anchor
        w = self._writer
        if w is None or os.path.dirname(w.path) != cfg.dir:
            if w is not None:
                # a runtime reconfigure moved the capture dir: close
                # the old session's file (index written) — dropping
                # the object unclosed would leak its fd
                try:
                    w.close()
                except OSError:
                    pass
            w = self._open_writer(cfg)
        batch_bytes = 0
        for i, (rec, code, lat_us) in enumerate(batch):
            # wall stamp derived here, off the hot path, from the
            # start-time anchor (one clock pair per recorder start)
            t = rec[_T]
            batch_bytes += w.write_fields(
                rec[_K], rec[_S], rec[_N], rec[_PAY], rec[_ATT], t,
                wall0 + (t - mono0) if t else wall0,
                rec[_O], rec[_P], rec[_L], code, lat_us)
            if w.bytes >= cfg.rotate_bytes:
                # rotation checked per RECORD: a burst drained in one
                # swap must not blow a single file far past the bound
                w.close()
                self._closed_files.append(w.path)
                with self._lock:
                    self.rotations += 1
                self._enforce_disk_budget(cfg)
                w = self._open_writer(cfg)
            if not (i + 1) % 64:
                # yield inside a long burst: an uninterrupted
                # multi-millisecond write loop convoys the event
                # thread behind the GIL switch interval
                time.sleep(0)
        w.flush()
        with self._lock:
            # these increments race start()'s counter reset when a
            # restart lands while the old writer is still draining:
            # unguarded they can resurrect a zeroed counter
            self.written += len(batch)
            # session total, not the active file's size — rotation
            # must not make the page's byte counter fall back to zero
            self.written_bytes += batch_bytes
        nwritten.add(len(batch))

    def _open_writer(self, cfg: CaptureConfig) -> CorpusWriter:
        self._file_seq += 1
        path = os.path.join(
            cfg.dir, f"capture-{os.getpid()}-{self._file_seq}{SUFFIX}")
        self._writer = CorpusWriter(path)
        return self._writer

    def _enforce_disk_budget(self, cfg: CaptureConfig) -> None:
        """Oldest CLOSED files go first; the active file is never
        deleted. Budget covers the whole capture dir (shard siblings
        included — one budget per operator intent, not per pid)."""
        try:
            entries = []
            active = self._writer.path if self._writer is not None else ""
            for name in os.listdir(cfg.dir):
                if not name.endswith(SUFFIX):
                    continue
                p = os.path.join(cfg.dir, name)
                if p == active:
                    continue
                st = os.stat(p)
                entries.append((st.st_mtime_ns, p, st.st_size))
            total = sum(sz for _, _, sz in entries)
            entries.sort()
            while total > cfg.disk_budget_bytes and entries:
                _, p, sz = entries.pop(0)
                os.remove(p)
                try:
                    os.remove(p + ".idx")
                except OSError:
                    pass
                total -= sz
                with self._lock:
                    self.deleted_files += 1
        except OSError:
            pass

    # ---------------------------------------------------------- surfaces
    def corpus_paths(self) -> List[str]:
        """Corpus files in the active (or last) capture dir — every
        shard's files, not just this pid's (the supervisor's download
        merges the whole dir)."""
        cfg = self._cfg
        if cfg is None or not cfg.dir or not os.path.isdir(cfg.dir):
            return []
        return sorted(os.path.join(cfg.dir, n)
                      for n in os.listdir(cfg.dir) if n.endswith(SUFFIX))

    def snapshot(self) -> dict:
        with self._lock:
            pending = len(self._q)
        cfg = self._cfg
        out = {
            "active": self._active, "legacy": self._legacy,
            "incident_mode": self._incident_mode,
            "config": cfg.to_dict() if cfg is not None else None,
            "pending": pending,
            "sampled": self.written + self.dropped_queue + pending,
            "written": self.written,
            "written_bytes": self.written_bytes,
            "dropped_queue": self.dropped_queue,
            "dropped_budget": self.dropped_budget,
            "rotations": self.rotations,
            "deleted_files": self.deleted_files,
            "pid": os.getpid(),
        }
        paths = self.corpus_paths()
        out["files"] = [{"path": p, "bytes": _fsize(p)} for p in paths]
        return out


def _fsize(p: str) -> int:
    try:
        return os.stat(p).st_size
    except OSError:
        return 0


def _legacy_dir() -> str:
    try:
        return flag("rpc_dump_dir")
    except KeyError:
        return ""        # rpc package not imported (bare tools)


def _legacy_budget() -> int:
    try:
        return int(flag("rpc_dump_max_requests_per_second"))
    except KeyError:
        return 0


_recorder = Recorder()


def global_recorder() -> Recorder:
    return _recorder


def start_capture(dir: Optional[str] = None, **overrides) -> dict:
    """Runtime control (the /capture page's start action): flags
    provide defaults, keyword overrides win. Returns the snapshot."""
    r = global_recorder()
    cfg = CaptureConfig.from_flags(dir=dir, **overrides)
    if not cfg.dir:
        raise ValueError("capture needs a directory (capture_dir flag "
                         "or dir= argument)")
    r.start(cfg)
    return r.snapshot()


def stop_capture() -> dict:
    r = global_recorder()
    r.stop()
    return r.snapshot()


def _postfork_reset() -> None:
    """Fork hygiene, IN PLACE (dispatch code may hold the recorder
    object): the child inherits the parent's queue (parent's in-flight
    records), a writer thread that did not survive the fork, and a
    CorpusWriter whose fd shares the parent's file offset through the
    shared open-file description. Fresh lock/queue/event, thread and
    writer dropped (the inherited fd closes with the writer object;
    the PARENT keeps its own reference so nothing of the parent's is
    torn). The CONFIG and active state survive, so a capturing shard
    child keeps capturing — into its own per-pid file (_open_writer
    names files by os.getpid()), and counters restart at zero."""
    r = _recorder
    if r._incident_mode:
        # the incident window belongs to the PARENT (its watchdog, its
        # spool): the child resumes the pre-window session state
        cfg, was_active, was_legacy = r._pre_incident or (None, False,
                                                          False)
        r._cfg = cfg
        r._active = bool(was_active and cfg is not None)
        r._legacy = was_legacy
    r._incident_mode = False
    r._pre_incident = None
    r._lock = threading.Lock()
    r._q = deque()
    r._q_bytes = 0
    r._wake = threading.Event()
    r._thread = None
    r._stopping = False
    r._writer = None         # per-pid file: the child opens its own
    r._file_seq = 0
    r._closed_files = []
    r._second = 0
    r._taken = 0
    r._clock_anchor = (time.time_ns(), time.monotonic_ns())
    r.written = r.written_bytes = 0
    r.dropped_queue = r.dropped_budget = 0
    r.rotations = r.deleted_files = 0


postfork.register("traffic.capture", _postfork_reset)
