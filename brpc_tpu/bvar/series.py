"""Multi-resolution trend rings over every exposed variable — the
time axis under /vars (the reference's bvar Series<T> + -save_series:
bvar/detail/series.h keeps 60s/60m/24h/30d rings per exposed var and
/vars plots them; our /timeline serves the same rings as JSON).

One ring set per exposed variable, stamped on the EXISTING global
sampler tick thread (bvar/window.py — the thread that already
snapshots every windowed reducer 1/s): 60 one-second buckets cascading
into 60 one-minute buckets cascading into 24 one-hour buckets, O(1)
per var per tick (the cascade combines 60 buckets once per minute —
amortized O(1)). Value semantics per kind:

  delta    — Adder-shaped cumulative counters: bucket = per-interval
             delta of get_value snapshots (never reset(): the Window
             sampler owns reset-mode sampling); cascade + shard merge
             SUM.
  last     — gauges (PassiveStatus/Status/Window readings): bucket =
             last reading; cascade keeps the last; shard merge applies
             the name-aware scalar rules merged /vars uses
             (shard_group.merge_var_values), so the two views cannot
             disagree on any gauge.
  max/min  — Maxer readings and instant-quantile gauges keep the max
             observed; Miner readings keep the MIN (told apart by the
             reducer's combine op); cascade + merge with the same
             extreme (a p99 spike — or a Miner's floor reading — must
             survive into the minute ring; averaging would erase it).
  quantile — LatencyRecorder composites: bucket = {count: per-interval
             delta, p50/p99/max: instant readings}; cascade and shard
             merge sum the counts and take per-field MAXIMA — pooled
             worst-case, never averaged (averaged percentiles are
             wrong; the merged /status percentiles pool raw reservoirs
             instead, this ring keeps the bounded conservative form).

Escape hatch: ``BRPC_TPU_BVAR_SERIES=0`` in the environment or the
``bvar_series_enabled`` flag parks the whole engine (ticks become a
single boolean check). The registry survives ``unexpose_all`` + a
re-expose at ``Server.start`` (the PR 2 lifecycle rule): a name that
re-appears under a NEW variable object keeps its ring history and
re-baselines its delta snapshot, so a restart never fabricates a
spike. Fork hygiene: the postfork registry clears the rings — a shard
child starts fresh while the parent's rings stay untouched.

The anomaly watchdog (bvar/anomaly.py) rides the same tick: every
stored bucket that matches the curated watch-key set feeds its
EWMA+MAD z-score pass. Sampler-thread discipline applies to this whole
module: everything reachable from ``series_sample_tick`` binds its
imports at module load (the PR 8 fd-hazard rule, enforced by
graftlint's sampler-no-lazy-import rule through the cross-module
marker recursion).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.variable import dump_exposed_variables
# the watchdog is sampler-tick code: bound at module load (anomaly
# imports only flags/stdlib at load — no cycle back into bvar), as
# DIRECT function imports so the lock model resolves the tick's call
# chain into anomaly.py (the sampler-no-lazy-import rule roots there)
from brpc_tpu.bvar.anomaly import (bind_watchdog_imports,
                                   is_watch_key as _is_watch_key,
                                   watchdog_sample_pass)

define_flag("bvar_series_enabled", True,
            "attach multi-resolution trend rings (60x1s -> 60x1m -> "
            "24x1h) to every exposed bvar on the sampler tick; serves "
            "/timeline and the /vars sparklines. BRPC_TPU_BVAR_SERIES=0 "
            "in the environment overrides to off")
define_flag("bvar_series_max_vars", 256,
            "most exposed variables tracked by the series engine "
            "(sorted by name; the rest are skipped, never sampled)")

SEC_BUCKETS = 60
MIN_BUCKETS = 60
HOUR_BUCKETS = 24

KIND_DELTA = "delta"
KIND_LAST = "last"
KIND_MAX = "max"
KIND_MIN = "min"
KIND_QUANTILE = "quantile"

_SPARK = "▁▂▃▄▅▆▇█"


def series_enabled() -> bool:
    """One boolean gate for the whole engine: env escape hatch first
    (an operator's BRPC_TPU_BVAR_SERIES=0 must win even over a /flags
    mutation), then the runtime flag."""
    if os.environ.get("BRPC_TPU_BVAR_SERIES", "1") == "0":
        return False
    return bool(flag("bvar_series_enabled"))


def sparkline(values, width: int = 30) -> str:
    """Unicode sparkline of the last ``width`` numeric values.
    Bounds: empty/non-numeric input -> "", a constant series renders
    at the floor glyph, min..max always span the full glyph ramp."""
    vals = [float(v) for v in values
            if isinstance(v, (int, float)) and not isinstance(v, bool)]
    vals = vals[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    span = hi - lo
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int((v - lo) / span * len(_SPARK)))]
                   for v in vals)


def _combine(kind: str, a, b):
    """Fold bucket ``b`` into accumulator ``a`` (the cascade op and
    the cross-shard per-bucket op share these semantics)."""
    if a is None:
        return b
    if b is None:
        return a
    if kind == KIND_DELTA:
        return a + b
    if kind == KIND_LAST:
        return b
    if kind == KIND_MAX:
        return max(a, b)
    if kind == KIND_MIN:
        return min(a, b)
    # quantile dict: counts sum, percentile fields keep the worst
    out = dict(a)
    out["count"] = (a.get("count", 0) or 0) + (b.get("count", 0) or 0)
    for k in ("p50", "p99", "max"):
        out[k] = max(a.get(k, 0) or 0, b.get(k, 0) or 0)
    return out


class _Ring:
    """One variable's three-level ring: seconds cascade into minutes
    cascade into hours on rollover."""

    __slots__ = ("kind", "sec", "min", "hr",
                 "_min_acc", "_min_n", "_hr_acc", "_hr_n")

    def __init__(self, kind: str):
        self.kind = kind
        self.sec: deque = deque(maxlen=SEC_BUCKETS)
        self.min: deque = deque(maxlen=MIN_BUCKETS)
        self.hr: deque = deque(maxlen=HOUR_BUCKETS)
        self._min_acc = None
        self._min_n = 0
        self._hr_acc = None
        self._hr_n = 0

    def push(self, t: int, value) -> None:
        self.sec.append((t, value))
        self._min_acc = _combine(self.kind, self._min_acc, value)
        self._min_n += 1
        if self._min_n >= SEC_BUCKETS:
            self.min.append((t, self._min_acc))
            self._hr_acc = _combine(self.kind, self._hr_acc,
                                    self._min_acc)
            self._hr_n += 1
            self._min_acc, self._min_n = None, 0
            if self._hr_n >= MIN_BUCKETS:
                self.hr.append((t, self._hr_acc))
                self._hr_acc, self._hr_n = None, 0

    def to_dict(self) -> dict:
        # live_sec/live_min: buckets not yet cascaded into the level
        # above — the seconds ring is a sliding WINDOW (it keeps
        # showing buckets a rolled minute already absorbed), so exact
        # accounting reads "minutes + the last live_sec seconds"
        return {"kind": self.kind,
                "sec": [[t, v] for t, v in self.sec],
                "min": [[t, v] for t, v in self.min],
                "hr": [[t, v] for t, v in self.hr],
                "live_sec": self._min_n, "live_min": self._hr_n}


class _Entry:
    __slots__ = ("ring", "vid", "prev", "touched")

    def __init__(self, kind: str, vid: int):
        self.ring = _Ring(kind)
        self.vid = vid          # id() of the backing Variable: a
        #                         re-exposed name re-baselines, never
        #                         fabricates a delta across objects
        self.prev = None        # previous cumulative snapshot (delta)
        self.touched = 0.0


def detect_kind(var) -> Optional[str]:
    """Duck-typed (no bvar-submodule imports — this runs on the
    sampler path and the latency/window modules import back into this
    package): LatencyRecorder shape first, then the reducer's declared
    SERIES_MODE, then 'numeric gauge'."""
    if hasattr(var, "_percentile") and hasattr(var, "latency_percentile"):
        return KIND_QUANTILE
    mode = getattr(var, "SERIES_MODE", None)
    if mode == "cumulative":
        return KIND_DELTA
    if mode == "delta":
        # Maxer vs Miner share the reducer shape; the combine op tells
        # them apart (a Miner's minima cascaded with max() would erase
        # exactly the floor readings a Miner exists to catch)
        op = getattr(var, "_op", None)
        if op is not None:
            try:
                if op(0, 1) == 0:
                    return KIND_MIN
            except Exception:
                pass
        return KIND_MAX
    return KIND_LAST


class SeriesCollector:
    """The process-wide ring registry. ``_lock`` is a LEAF (LOCK_ORDER
    row: bvar/series.py): it guards ring/entry mutation only — every
    variable read (get_value may call arbitrary PassiveStatus
    callbacks and take reducer locks) happens BEFORE the lock is
    taken, and nothing is called out under it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._declared: Dict[str, str] = {}

    # -------------------------------------------------------- declare
    def declare_kind(self, name: str, kind: str) -> None:
        """Name-declared semantics override detection: a monotone
        PassiveStatus (server_processed) graphs as qps only when its
        series knows it is a counter."""
        with self._lock:
            self._declared[name] = kind

    # ---------------------------------------------------------- ticks
    def collect_readings(self) -> List[Tuple[str, int, str, object]]:
        """Phase 1, NO lock held: read every tracked variable.
        Non-numeric readings are skipped (Status strings, dict-valued
        passives)."""
        cap = max(1, int(flag("bvar_series_max_vars")))
        with self._lock:
            declared = dict(self._declared)
        pairs = dump_exposed_variables("")
        if len(pairs) > cap:
            # over the cap: the watchdog's keys and every declared
            # series keep their slots FIRST — a labeled-cell explosion
            # must not silently evict server_errors because 's' sorts
            # late — the remainder fills alphabetically
            priority = [(n, v) for n, v in pairs
                        if n in declared or _is_watch_key(n)]
            rest = [(n, v) for n, v in pairs
                    if n not in declared and not _is_watch_key(n)]
            pairs = (priority + rest)[:cap]
        out: List[Tuple[str, int, str, object]] = []
        for name, var in pairs:
            kind = declared.get(name) or detect_kind(var)
            try:
                if kind == KIND_QUANTILE:
                    raw = {"count": int(var.count()),
                           "p50": float(var.latency_percentile(0.5)),
                           "p99": float(var.latency_percentile(0.99)),
                           "max": float(var.max_latency() or 0)}
                else:
                    v = var.get_value()
                    if not isinstance(v, (int, float)) or \
                            isinstance(v, bool):
                        continue
                    raw = v
            except Exception:
                continue    # a raising passive must not kill the tick
            out.append((name, id(var), kind, raw))
        return out

    def store_readings(self, readings, t: int) -> Dict[str, float]:
        """Phase 2, under the leaf lock: turn readings into buckets.
        Returns the watch points for the anomaly pass (key -> the
        bucket value just stored, numeric only)."""
        points: Dict[str, float] = {}
        now = time.monotonic()
        with self._lock:
            for name, vid, kind, raw in readings:
                e = self._entries.get(name)
                if e is None or e.ring.kind != kind:
                    e = self._entries[name] = _Entry(kind, vid)
                if e.vid != vid:
                    # re-exposed under a new object (unexpose_all +
                    # Server.start): keep the ring, re-baseline
                    e.vid = vid
                    e.prev = None
                e.touched = now
                if kind == KIND_DELTA:
                    prev, e.prev = e.prev, raw
                    bucket = raw - prev if prev is not None else 0
                    if bucket < 0:      # counter reset: re-baseline
                        bucket = 0
                elif kind == KIND_QUANTILE:
                    prev, e.prev = e.prev, raw["count"]
                    dc = raw["count"] - prev if prev is not None else 0
                    bucket = {"count": max(0, dc), "p50": raw["p50"],
                              "p99": raw["p99"], "max": raw["max"]}
                else:
                    bucket = raw
                e.ring.push(t, bucket)
                if kind == KIND_QUANTILE:
                    # the .p99 track goes through the same predicate
                    # as every other key: a pinned anomaly_watch_filter
                    # must silence it too (the smoke's exactly-one-
                    # incident determinism depends on that)
                    key = name + ".p99"
                    if _is_watch_key(key):
                        points[key] = bucket["p99"]
                elif _is_watch_key(name):
                    points[name] = float(bucket)
            self._prune_locked(now)
        return points

    def _prune_locked(self, now: float) -> None:
        cap = max(1, int(flag("bvar_series_max_vars")))
        if len(self._entries) <= cap:
            return
        # over the cap (mass re-expose churn): drop least-recently
        # touched names first — frozen history loses to live series
        for name in sorted(self._entries,
                           key=lambda n: self._entries[n].touched):
            if len(self._entries) <= cap:
                break
            del self._entries[name]

    # ---------------------------------------------------------- reads
    def has_series(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def tracked_names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def spark(self, name: str, width: int = 30) -> str:
        """Seconds-level sparkline for the /vars inline column
        (quantile series render their p99 track)."""
        with self._lock:
            e = self._entries.get(name)
            if e is None or len(e.ring.sec) < 2:
                return ""
            vals = [v for _, v in e.ring.sec]
            kind = e.ring.kind
        if kind == KIND_QUANTILE:
            vals = [v.get("p99", 0) for v in vals]
        return sparkline(vals, width)

    def dump_series(self, names: Optional[List[str]] = None,
                    prefix: str = "",
                    max_vars: Optional[int] = None) -> Dict[str, dict]:
        with self._lock:
            picked = []
            for name in sorted(self._entries):
                if names is not None and name not in names:
                    continue
                if prefix and not name.startswith(prefix):
                    continue
                picked.append(name)
                if max_vars is not None and len(picked) >= max_vars:
                    break
            return {n: self._entries[n].ring.to_dict() for n in picked}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


# ------------------------------------------------------------ singleton

_collector: Optional[SeriesCollector] = None
_collector_lock = threading.Lock()


def global_series() -> SeriesCollector:
    global _collector
    if _collector is None:
        with _collector_lock:
            if _collector is None:
                _collector = SeriesCollector()
    return _collector


def declare_series_kind(name: str, kind: str) -> None:
    global_series().declare_kind(name, kind)


# tick serialization gate: collect+store must not interleave between
# two tickers (the background sampler vs a smoke's manual wall_t
# drive) — an out-of-order store would hit the delta clamp and
# re-baseline DOWNWARD, over-counting the next interval. Non-blocking:
# the loser skips its stamp (the winner's pass covers the interval —
# sums stay an exact partition either way). acquire/release, not
# `with`: nothing may nest inside, it is a mutual-exclusion gate.
_tick_serial = threading.Lock()


def series_sample_tick(wall_t: Optional[int] = None) -> None:
    """The per-second stamp, called by the global sampler's tick
    (bvar/window.py) — and by tests driving time by hand (wall_t pins
    the bucket stamp; buckets are wall-epoch so shard merges align).
    Never raises: the sampler thread must not die for a ring."""
    if not series_enabled():
        return
    if not _tick_serial.acquire(blocking=False):
        return
    try:
        col = global_series()
        t = int(time.time()) if wall_t is None else int(wall_t)
        points = col.store_readings(col.collect_readings(), t)
        watchdog_sample_pass(points, t)
    except Exception:
        pass
    finally:
        _tick_serial.release()


def ensure_series() -> None:
    """Server.start's hook (caller thread, NOT the sampler thread):
    bind the watchdog's annotation imports before the sampler can need
    them (the PR 8 rule), and make sure the global sampler's tick
    thread is running even in a process with no windowed reducers."""
    bind_watchdog_imports()
    if not series_enabled():
        return
    from brpc_tpu.bvar import window as _window
    _window.global_sampler._ensure_thread()


# --------------------------------------------------------------- merges

def merge_timeline_states(states: List[Tuple[Optional[int], dict]],
                          names: Optional[List[str]] = None,
                          prefix: str = "") -> dict:
    """Supervisor-side merge of per-shard /timeline payloads (each a
    (shard_index, timeline_page_payload dict) pair from the dumps):
    per-bucket counters SUM, maxima MAX, quantile series pool their
    per-field worst case with counts summed — never averaged — and
    gauges apply the same name-aware scalar rules merged /vars uses
    (shard_group.merge_var_values), so the two merged views agree on
    every gauge by construction. Incidents concatenate, tagged with
    their shard."""
    from brpc_tpu.rpc.shard_group import merge_var_values
    out: dict = {"mode": "shard_group", "shards_reporting": len(states),
                 "enabled": any(s.get("enabled") for _, s in states),
                 "resolution": {"sec": SEC_BUCKETS, "min": MIN_BUCKETS,
                                "hr": HOUR_BUCKETS}}
    merged: Dict[str, dict] = {}
    # shards roll their minute/hour buckets at their OWN 60th push
    # (ring-relative, and sampler periods drift past 1s), so coarse
    # buckets align on the epoch grid here — without this, two shards'
    # minutes almost never share a t key and "counters sum" would be
    # an interleave, not a sum
    grid = {"sec": 1, "min": 60, "hr": 3600}
    for _, st in states:
        for name, ser in (st.get("series") or {}).items():
            if names is not None and name not in names:
                continue
            if prefix and not name.startswith(prefix):
                continue
            m = merged.setdefault(name, {"kind": ser.get("kind"),
                                         "sec": {}, "min": {}, "hr": {}})
            kind = m["kind"]
            for level in ("sec", "min", "hr"):
                buckets = m[level]
                step = grid[level]
                for t, v in ser.get(level) or ():
                    t -= t % step
                    if kind == KIND_LAST:
                        buckets.setdefault(t, []).append(v)
                    else:
                        buckets[t] = _combine(kind, buckets.get(t), v)
    series: Dict[str, dict] = {}
    for name, m in merged.items():
        d = {"kind": m["kind"]}
        for level in ("sec", "min", "hr"):
            if m["kind"] == KIND_LAST:
                d[level] = [[t, merge_var_values(vals, name=name)]
                            for t, vals in sorted(m[level].items())]
            else:
                d[level] = [[t, v] for t, v in sorted(m[level].items())]
        series[name] = d
    out["series"] = series
    incidents = []
    for shard, st in states:
        for inc in st.get("incidents") or ():
            row = dict(inc)
            row["shard"] = shard
            incidents.append(row)
    incidents.sort(key=lambda r: (r.get("opened_t") or 0,
                                  r.get("shard") or 0))
    out["incidents"] = incidents
    keys = set()
    for _, st in states:
        keys.update(st.get("watch_keys") or ())
    out["watch_keys"] = sorted(keys)
    return out


# ------------------------------------------------------------- postfork

def _postfork_reset() -> None:
    """Fork hygiene: the rings describe the PARENT's counters (a
    shard's private bvar store diverges from the first request on) and
    the leaf lock — or the tick gate — may be mid-hold at fork time.
    The child starts with an empty registry; the parent's rings are
    untouched."""
    global _collector, _collector_lock, _tick_serial
    _collector = None
    _collector_lock = threading.Lock()
    _tick_serial = threading.Lock()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the registry it resets)

_postfork.register("bvar.series", _postfork_reset)
