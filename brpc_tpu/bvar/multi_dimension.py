"""MultiDimension: labeled (prometheus-style) metrics — the reference's
mbvar (bvar/multi_dimension{_inl}.h, mvariable.cpp).

One MultiDimension owns a family of per-label-combination stats created
on demand from a factory: ``qps = MultiDimension(["method", "status"],
Adder); qps.get_stats(("Echo", "ok")).add(1)``. get_value() snapshots
{labels_tuple: value}; the prometheus dumper renders proper
``name{method="Echo",status="ok"} N`` lines."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.bvar.variable import Variable


class MultiDimension(Variable):
    def __init__(self, label_names: Sequence[str],
                 stat_factory: Callable[[], Variable]):
        super().__init__()
        self._label_names = tuple(label_names)
        self._factory = stat_factory
        self._stats: Dict[Tuple, Variable] = {}
        self._lock = threading.Lock()

    @property
    def label_names(self) -> Tuple[str, ...]:
        return self._label_names

    def _key(self, label_values: Sequence) -> Tuple:
        key = tuple(str(v) for v in label_values)
        if len(key) != len(self._label_names):
            raise ValueError(
                f"expected {len(self._label_names)} labels "
                f"{self._label_names}, got {len(key)}")
        return key

    def get_stats(self, label_values: Sequence) -> Variable:
        """The per-combination stat, created on first use (mbvar
        get_stats). Hot path after creation is one dict lookup."""
        key = self._key(label_values)
        stat = self._stats.get(key)
        if stat is not None:
            return stat
        with self._lock:
            stat = self._stats.get(key)
            if stat is None:
                stat = self._factory()
                # publish under the lock; dict assignment is atomic so
                # lock-free readers see either nothing or the final stat
                self._stats[key] = stat
            return stat

    def has_stats(self, label_values: Sequence) -> bool:
        return self._key(label_values) in self._stats

    def delete_stats(self, label_values: Sequence) -> None:
        with self._lock:
            self._stats.pop(self._key(label_values), None)

    def count_stats(self) -> int:
        return len(self._stats)

    def list_stats(self) -> List[Tuple]:
        return sorted(self._stats.keys())

    def get_value(self) -> Dict[Tuple, object]:
        with self._lock:
            items = list(self._stats.items())
        return {k: v.get_value() for k, v in items}

    def labeled_items(self) -> List[Tuple[Tuple, object]]:
        """(label_values_tuple, value) pairs — the prometheus dumper
        reads labels through this instead of get_value(), so a subclass
        may flatten get_value() keys for JSON consumers (/vars) without
        losing its label structure in the metrics dump."""
        with self._lock:
            items = list(self._stats.items())
        return [(k, v.get_value()) for k, v in items]

    def describe(self) -> str:
        return (f"MultiDimension({','.join(self._label_names)}: "
                f"{self.count_stats()} series)")
