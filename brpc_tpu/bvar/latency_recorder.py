"""LatencyRecorder: qps + latency avg + percentiles + max in one composite
(bvar/latency_recorder.h:75)."""

from __future__ import annotations

from typing import Optional

from brpc_tpu.bvar.reducer import Adder, IntRecorder, Maxer
from brpc_tpu.bvar.percentile import Percentile
from brpc_tpu.bvar.variable import Variable
from brpc_tpu.bvar.window import PerSecond, Sampler


class LatencyRecorder(Variable):
    def __init__(self, window_size: int = 10, sampler: Optional[Sampler] = None):
        super().__init__()
        self._latency = IntRecorder()
        self._max_latency = Maxer()
        self._percentile = Percentile()
        self._count = Adder(0)
        self._qps = PerSecond(self._count, window_size, sampler)

    def record(self, latency_us: float):
        self._latency.record(latency_us)
        self._max_latency.update(latency_us)
        self._percentile.add(latency_us)
        self._count.add(1)

    def record_batch(self, avg_latency_us: float, n: int):
        """Account ``n`` calls served as one batch (the native serving
        loop measures the batch, not each call): the average lands in
        sum/count exactly, and contributes one percentile sample —
        reservoir percentiles are sampled estimates either way."""
        self._latency.record(avg_latency_us, n)
        self._max_latency.update(avg_latency_us)
        self._percentile.add(avg_latency_us)
        self._count.add(n)

    __lshift__ = lambda self, v: (self.record(v), self)[1]

    def latency(self) -> float:
        return self._latency.average()

    def latency_percentile(self, ratio: float) -> float:
        return self._percentile.get_percentile(ratio)

    def max_latency(self) -> float:
        return self._max_latency.get_value() or 0

    def count(self) -> int:
        return self._count.get_value()

    def qps(self) -> float:
        return self._qps.get_value()

    def get_value(self):
        return {
            "count": self.count(),
            "qps": self.qps(),
            "latency_avg_us": self.latency(),
            "latency_p50_us": self.latency_percentile(0.5),
            "latency_p90_us": self.latency_percentile(0.9),
            "latency_p99_us": self.latency_percentile(0.99),
            "latency_p999_us": self.latency_percentile(0.999),
            "max_latency_us": self.max_latency(),
        }

    def expose(self, name: str):
        super().expose(name)
        return self
