"""Prometheus text-format dump of exposed variables
(builtin/prometheus_metrics_service.cpp equivalent)."""

from __future__ import annotations

from typing import List

from brpc_tpu.bvar.variable import dump_exposed


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def dump_prometheus(prefix: str = "") -> str:
    lines: List[str] = []
    for name, value in dump_exposed(prefix):
        mname = _sanitize(name)
        if isinstance(value, dict):
            for k, v in value.items():
                if isinstance(v, (int, float)):
                    lines.append(f"{mname}_{_sanitize(str(k))} {v}")
        elif isinstance(value, bool):
            lines.append(f"{mname} {int(value)}")
        elif isinstance(value, (int, float)):
            lines.append(f"{mname} {value}")
        # non-numeric vars are skipped, like the reference's dumper
    return "\n".join(lines) + ("\n" if lines else "")
