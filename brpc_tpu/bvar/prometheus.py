"""Prometheus text-format dump of exposed variables
(builtin/prometheus_metrics_service.cpp equivalent)."""

from __future__ import annotations

from typing import List

from brpc_tpu.bvar.variable import dump_exposed_variables


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def dump_prometheus_items(items) -> str:
    """Prometheus text from (name, value) pairs instead of live
    Variables — the shard supervisor's merged dump renders through
    this (its numbers come from the per-shard JSON stores, not from
    this process's registry). Same scalar/composite rules as
    dump_prometheus; non-numeric values are skipped."""
    lines: List[str] = []
    for name, value in items:
        mname = _sanitize(name)
        if isinstance(value, dict):
            for k, v in value.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines.append(f"{mname}_{_sanitize(str(k))} {v}")
        elif isinstance(value, bool):
            lines.append(f"{mname} {int(value)}")
        elif isinstance(value, (int, float)):
            lines.append(f"{mname} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


def dump_prometheus(prefix: str = "") -> str:
    from brpc_tpu.bvar.multi_dimension import MultiDimension
    lines: List[str] = []
    for name, var in dump_exposed_variables(prefix):
        mname = _sanitize(name)
        if isinstance(var, MultiDimension):
            # labeled series: name{k="v",...} value — labels come from
            # labeled_items(), NOT get_value() (a subclass may flatten
            # get_value keys for JSON consumers)
            label_names = [_sanitize(ln) for ln in var.label_names]
            for key, v in sorted(var.labeled_items()):
                if isinstance(v, dict):
                    # composite stat (e.g. LatencyRecorder): one line per
                    # numeric component
                    for ck, cv in v.items():
                        if isinstance(cv, (int, float)):
                            labels = ",".join(
                                f'{ln}="{_escape_label(str(kv))}"'
                                for ln, kv in zip(label_names, key))
                            lines.append(
                                f"{mname}_{_sanitize(str(ck))}{{{labels}}} {cv}")
                elif isinstance(v, (int, float)):
                    labels = ",".join(f'{ln}="{_escape_label(str(kv))}"'
                                      for ln, kv in zip(label_names, key))
                    lines.append(f"{mname}{{{labels}}} {v}")
            continue
        value = var.get_value()
        if isinstance(value, dict):
            for k, v in value.items():
                if isinstance(v, (int, float)):
                    lines.append(f"{mname}_{_sanitize(str(k))} {v}")
        elif isinstance(value, bool):
            lines.append(f"{mname} {int(value)}")
        elif isinstance(value, (int, float)):
            lines.append(f"{mname} {value}")
        # non-numeric vars are skipped, like the reference's dumper
    return "\n".join(lines) + ("\n" if lines else "")
