"""Collector: the global sampling funnel for heavyweight samples with a
per-second budget (bvar/collector.{h,cpp} — what bounds rpcz span and
rpc_dump overhead in the reference).

Submission is lock-cheap and never blocks the caller: a token bucket
admits at most ``samples_per_second``; admitted samples land in a
bounded ring. Consumers drain() or snapshot()."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, List, Optional

from brpc_tpu.bvar.reducer import Adder


class Collector:
    def __init__(self, samples_per_second: int = 1000,
                 max_pending: int = 10_000, name: str = ""):
        self._rate = samples_per_second
        self._ring: Deque[Any] = deque(maxlen=max_pending)
        self._lock = threading.Lock()
        self._window_start = time.monotonic()
        self._window_used = 0
        self.nsubmitted = Adder(0)
        self.nsampled = Adder(0)
        self.ndropped = Adder(0)
        if name:
            self.nsubmitted.expose(f"{name}_submitted")
            self.nsampled.expose(f"{name}_sampled")
            self.ndropped.expose(f"{name}_dropped")

    def submit(self, sample: Any) -> bool:
        """True if admitted within this second's budget."""
        self.nsubmitted.add(1)
        now = time.monotonic()
        with self._lock:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._window_used = 0
            if self._window_used >= self._rate:
                admitted = False
            else:
                self._window_used += 1
                self._ring.append(sample)
                admitted = True
        if admitted:
            self.nsampled.add(1)
        else:
            self.ndropped.add(1)
        return admitted

    def drain(self) -> List[Any]:
        with self._lock:
            out, self._ring = list(self._ring), deque(
                maxlen=self._ring.maxlen)
        return out

    def snapshot(self, n: Optional[int] = None) -> List[Any]:
        with self._lock:
            items = list(self._ring)
        return items[-n:] if n else items

    def set_rate(self, samples_per_second: int) -> None:
        with self._lock:
            self._rate = samples_per_second
