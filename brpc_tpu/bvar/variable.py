"""Variable registry: expose/describe/dump (bvar/variable.h:102).

Every metric can be exposed under a globally-unique name and then appears
in /vars, the prometheus dump, and window samplers.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Variable"] = {}


class Variable:
    """Base of every metric. Subclasses implement get_value()."""

    def __init__(self) -> None:
        self._name: Optional[str] = None

    # -- value -----------------------------------------------------------
    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        return str(self.get_value())

    # -- registry --------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        return self._name

    def expose(self, name: str) -> "Variable":
        name = name.strip().replace(" ", "_")
        with _registry_lock:
            old = _registry.get(name)
            if old is not None and old is not self:
                old._name = None
            _registry[name] = self
            self._name = name
        return self

    def hide(self) -> None:
        with _registry_lock:
            if self._name and _registry.get(self._name) is self:
                del _registry[self._name]
            self._name = None


def expose(name: str, var: Variable) -> Variable:
    return var.expose(name)


def dump_exposed(prefix: str = "") -> List[Tuple[str, object]]:
    """Snapshot of (name, value) for all exposed vars, sorted by name."""
    with _registry_lock:
        items = [(n, v) for n, v in _registry.items() if n.startswith(prefix)]
    return sorted((n, v.get_value()) for n, v in items)


def dump_exposed_variables(prefix: str = "") -> List[Tuple[str, "Variable"]]:
    """Snapshot of (name, variable) — for dumpers that need the variable
    itself (e.g. prometheus labeling of MultiDimension series)."""
    with _registry_lock:
        return sorted((n, v) for n, v in _registry.items()
                      if n.startswith(prefix))


def describe_exposed(name: str) -> Optional[str]:
    with _registry_lock:
        v = _registry.get(name)
    return v.describe() if v is not None else None


def unexpose_all() -> None:
    """Test helper."""
    with _registry_lock:
        for v in list(_registry.values()):
            v._name = None
        _registry.clear()


def _postfork_reset() -> None:
    """Fork hygiene: the registry contents are plain references (each
    shard keeps its copy and its counters diverge privately — that is
    the per-shard bvar store), but the lock may have been held by a
    parent thread mid-expose at fork time."""
    global _registry_lock
    _registry_lock = threading.Lock()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the registry it guards)

_postfork.register("bvar.variable", _postfork_reset)


def _bvar_census() -> dict:
    """Resource census: exposed-variable count (per-connection or
    per-method bvar leaks show up here long before they hurt)."""
    with _registry_lock:
        return {"count": len(_registry)}


from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the registry it measures)

_census.register("bvar", _bvar_census)
