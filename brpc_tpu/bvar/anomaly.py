"""Anomaly watchdog: EWMA+MAD z-scores over the curated watch-key set,
riding the bvar sampler tick (bvar/series.py hands it every stored
bucket that matches the watch predicate).

Post-mortems start from "when did it go wrong", and /timeline's rings
only answer that if someone knows where to look. The watchdog watches
the keys an operator would: error counters, shed counters, latency p99
tracks, queue-delay, device-lane failed/leaked bytes, capture drops —
and turns a statistical break in any of them into an INCIDENT record:

  * per key, an exponentially-weighted mean and an EWMA of absolute
    deviation (the MAD estimator's online form); a bucket whose
    z-score ``(x - mean) / (1.4826 * mad)`` clears ``anomaly_z_open``
    after ``anomaly_warmup_ticks`` observations raises an alert —
    upward breaks only (an error counter going quiet is recovery, not
    an incident);
  * alerts in one tick coalesce into ONE incident (a fault storm bumps
    sheds + errors + p99 together — three records for one cause is
    noise); later alerting keys attach to the open incident; the
    incident closes after ``anomaly_close_ticks`` consecutive calm
    ticks and the record (bounded ring) keeps open/close stamps, the
    implicated vars and their peak z/values;
  * an opening incident ANNOTATES the in-window rpcz spans (the
    requests that lived through the break carry ``incident #N`` in
    /rpcz) and stamps the flight recorder's live continuous-profile
    window label, so the profile window covering the break is marked
    in /hotspots?mode=continuous.

Everything here is sampler-thread code: the span/flight-recorder
collaborators are bound by ``bind_watchdog_imports()`` on the CALLER
thread (Server.start via series.ensure_series) — never imported at
sample time (the PR 8 fd-hazard rule; graftlint's
sampler-no-lazy-import rule walks this module through the tick
entrypoints' marker names). Determinism: incident open/close is a pure
function of the value sequence — same synthetic series, same incident
records, every run.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from brpc_tpu.butil.flags import define_flag, flag

define_flag("anomaly_watchdog_enabled", True,
            "run the EWMA+MAD anomaly watchdog over the curated "
            "watch keys on every series tick")
define_flag("anomaly_z_open", 6.0,
            "z-score a watched bucket must clear to raise an alert")
define_flag("anomaly_z_close", 3.0,
            "z-score below which a tick counts as calm for an open "
            "incident")
define_flag("anomaly_warmup_ticks", 5,
            "observations a key needs before it may alert (a fresh "
            "key's first reading is not an anomaly)")
define_flag("anomaly_close_ticks", 5,
            "consecutive calm ticks that close an open incident")
define_flag("anomaly_max_incidents", 64,
            "incident records kept in the bounded ring")
define_flag("anomaly_watch_filter", "",
            "comma-separated allowlist narrowing the watch-key set "
            "(empty = the full curated predicate); smokes pin this "
            "for determinism")

_EWMA_ALPHA = 0.25
_MAD_SCALE = 1.4826            # MAD -> sigma under normality
_SPAN_WINDOW_US = 5_000_000    # annotate spans that ended in the last 5s
_SPAN_ANNOTATE_MAX = 16

# annotation collaborators, bound on the CALLER thread by
# bind_watchdog_imports (never at sample time): rpc.span's collector
# ring, the flight recorder's live window label, and the incident
# manager (capture-on-anomaly, incident/manager.py)
_span_mod = None
_fr_mod = None
_inc_mod = None


def bind_watchdog_imports() -> None:
    """One-time import binding for the watchdog's annotation targets;
    runs on the thread that starts the serving stack (Server.start),
    mirroring flight_recorder._bind_sampler_imports."""
    global _span_mod, _fr_mod, _inc_mod
    if _fr_mod is not None:
        return
    from brpc_tpu.builtin import flight_recorder as fr
    from brpc_tpu.incident import manager as im
    from brpc_tpu.rpc import span as sm
    im.bind_incident_imports()
    _span_mod, _fr_mod, _inc_mod = sm, fr, im


def is_watch_key(name: str) -> bool:
    """The curated predicate: error counters, sheds, queue delay,
    device-lane failed/leaked, capture drops, and latency p99 tracks —
    both *_p99_us gauges and the ``<name>.p99`` track every quantile
    series derives. A set ``anomaly_watch_filter`` replaces the
    predicate wholesale (exact names only), so a pinned filter also
    silences the quantile tracks — the smokes' determinism contract."""
    filt = flag("anomaly_watch_filter")
    if filt:
        return name in {k.strip() for k in str(filt).split(",")
                        if k.strip()}
    return (name.endswith("_shed") or "error" in name
            or "queue_delay" in name or name.endswith("_p99_us")
            or name.endswith(".p99")
            or name.endswith("dropped_queue")
            or name.endswith("dropped_budget")
            or "leaked" in name or "unpulled" in name
            or name.startswith("chaos_injected")
            # serving-lane default arms (the TTFT watchdog): the
            # instant-max p99 gauge and the pooled recorder's quantile
            # track already match the *_p99_us/.p99 suffixes above;
            # the explicit prefixes keep the tok/s trend and any
            # future serving_ttft_* key in the set by name
            or name.startswith("serving_ttft")
            or name.startswith("serving_tokens_per_second"))


class _KeyState:
    __slots__ = ("mean", "mad", "n")

    def __init__(self):
        self.mean = 0.0
        self.mad = 0.0
        self.n = 0


class Incident:
    __slots__ = ("id", "opened_t", "closed_t", "keys", "peak_z",
                 "peak_value", "peak_key", "baseline", "calm",
                 "spans_annotated")

    def __init__(self, iid: int, t: int):
        self.id = iid
        self.opened_t = t
        self.closed_t: Optional[int] = None
        self.keys: List[str] = []
        self.peak_z = 0.0
        self.peak_value = 0.0
        self.peak_key = ""
        self.baseline = 0.0
        self.calm = 0
        self.spans_annotated = 0

    def to_dict(self) -> dict:
        return {"id": self.id, "opened_t": self.opened_t,
                "closed_t": self.closed_t,
                "state": "closed" if self.closed_t is not None
                else "open",
                "keys": list(self.keys),
                "peak_key": self.peak_key,
                "peak_z": round(self.peak_z, 2),
                "peak_value": round(self.peak_value, 3),
                "baseline": round(self.baseline, 3),
                "spans_annotated": self.spans_annotated}


class AnomalyWatchdog:
    """``_lock`` is a LEAF (LOCK_ORDER row: bvar/anomaly.py): it
    guards key-state and the incident ring only; span/flight-recorder
    annotation fires AFTER the lock is released (annotating under it
    would nest foreign locks beneath a sampler-tick leaf)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._keys: Dict[str, _KeyState] = {}
        self._incidents: deque = deque(
            maxlen=int(flag("anomaly_max_incidents")))
        self._open: Optional[Incident] = None
        self._next_id = 1

    # ----------------------------------------------------------- tick
    def watchdog_pass(self, points: Dict[str, float], t: int) -> None:
        """One tick's pass (unique verb — generic names mint false
        lock-graph edges through the unique-method fallback, the PR 11
        lesson). ``points`` is {watch key: the bucket value the series
        engine just stored}."""
        if not flag("anomaly_watchdog_enabled"):
            return
        warmup = int(flag("anomaly_warmup_ticks"))
        z_open = float(flag("anomaly_z_open"))
        z_close = float(flag("anomaly_z_close"))
        close_ticks = int(flag("anomaly_close_ticks"))
        opened: Optional[Incident] = None
        closed: Optional[Incident] = None
        with self._lock:
            alerts = []
            any_hot = False
            for key in sorted(points):
                x = float(points[key])
                st = self._keys.get(key)
                if st is None:
                    st = self._keys[key] = _KeyState()
                dev = abs(x - st.mean)
                denom = max(_MAD_SCALE * st.mad, 1.0,
                            0.02 * abs(st.mean))
                z = (x - st.mean) / denom          # upward breaks only
                if st.n >= warmup:
                    if z >= z_open:
                        alerts.append((key, x, z, st.mean))
                    if z >= z_close:
                        any_hot = True
                # update AFTER scoring: the spike must not vote on the
                # baseline it is judged against
                st.mean += _EWMA_ALPHA * (x - st.mean)
                st.mad += _EWMA_ALPHA * (dev - st.mad)
                st.n += 1
            if alerts:
                inc = self._open
                if inc is None:
                    inc = Incident(self._next_id, t)
                    self._next_id += 1
                    self._incidents.append(inc)
                    self._open = inc
                    opened = inc
                inc.calm = 0
                for key, x, z, mean in alerts:
                    if key not in inc.keys:
                        inc.keys.append(key)
                    if z > inc.peak_z:
                        inc.peak_z, inc.peak_value = z, x
                        inc.peak_key, inc.baseline = key, mean
            elif self._open is not None and not any_hot:
                self._open.calm += 1
                if self._open.calm >= close_ticks:
                    self._open.closed_t = t
                    closed = self._open
                    self._open = None
        if opened is not None:
            self._stamp_incident(opened)
        # capture-on-anomaly hand-off, outside the leaf lock; called
        # every tick (the manager's idle early-out is one attribute
        # check) so an armed window keeps counting down on calm ticks
        im = _inc_mod
        if im is not None:
            im.incident_sample_tick(opened, closed, t)

    # ----------------------------------------------------- annotation
    def _stamp_incident(self, inc: Incident) -> None:
        """Outside every lock: mark the rpcz spans that ended inside
        the break window and the flight recorder's live profile
        window. Best-effort — an annotation failure must never cost
        the sampler thread."""
        label = f"incident #{inc.id}: " + ",".join(inc.keys)
        sm, fr = _span_mod, _fr_mod
        if sm is not None:
            try:
                cutoff = time.monotonic_ns() // 1000 - _SPAN_WINDOW_US
                n = 0
                for span in reversed(sm.global_collector.recent(64)):
                    if span.end_us and span.end_us >= cutoff:
                        span.annotate(
                            f"{label} z={inc.peak_z:.1f} "
                            f"peak={inc.peak_value:g}")
                        n += 1
                        if n >= _SPAN_ANNOTATE_MAX:
                            break
                inc.spans_annotated = n
            except Exception:
                pass
        if fr is not None:
            try:
                fr.global_recorder().note_incident(
                    f"#{inc.id} {inc.peak_key or inc.keys[0]}")
            except Exception:
                pass

    # ---------------------------------------------------------- reads
    def incident_snapshot(self) -> List[dict]:
        with self._lock:
            return [inc.to_dict() for inc in self._incidents]

    def tracked_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._keys)

    def reset(self) -> None:
        with self._lock:
            self._keys.clear()
            self._incidents.clear()
            self._open = None
            self._next_id = 1


# ------------------------------------------------------------ singleton

_watchdog: Optional[AnomalyWatchdog] = None
_watchdog_lock = threading.Lock()


def global_watchdog() -> AnomalyWatchdog:
    global _watchdog
    if _watchdog is None:
        with _watchdog_lock:
            if _watchdog is None:
                _watchdog = AnomalyWatchdog()
    return _watchdog


def watchdog_sample_pass(points: Dict[str, float], t: int) -> None:
    """The series tick's entry (bvar/series.py) — marker-named so the
    sampler-no-lazy-import rule roots its closure here."""
    global_watchdog().watchdog_pass(points, t)


def _postfork_reset() -> None:
    """Fork hygiene: the key baselines and incidents describe the
    PARENT's traffic and the leaf lock may be mid-hold at fork time.
    A shard child starts with a fresh watchdog."""
    global _watchdog, _watchdog_lock
    _watchdog = None
    _watchdog_lock = threading.Lock()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singleton it resets)

_postfork.register("bvar.anomaly", _postfork_reset)
