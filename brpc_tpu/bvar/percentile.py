"""Percentile estimator: per-thread reservoir samples combined on read
(bvar/detail/percentile.{h,cpp}).

Each thread keeps a bounded reservoir; get_percentile merges reservoirs.
Like the reference, accuracy degrades gracefully under load instead of the
write path ever blocking.
"""

from __future__ import annotations

import threading
from typing import List

from brpc_tpu.butil.fast_rand import fast_rand_less_than
from brpc_tpu.bvar.variable import Variable

_RESERVOIR_SIZE = 1024


class _Reservoir:
    __slots__ = ("samples", "num_added")

    def __init__(self):
        self.samples: List[float] = []
        self.num_added = 0

    def add(self, v: float):
        self.num_added += 1
        s = self.samples  # snapshot the binding: reset() may swap in a new list
        if len(s) < _RESERVOIR_SIZE:
            s.append(v)
        else:
            i = fast_rand_less_than(self.num_added)
            if i < _RESERVOIR_SIZE:
                try:
                    s[i] = v
                except IndexError:
                    pass  # lost the race with reset(); drop one sample


class Percentile(Variable):
    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._reservoirs: dict = {}
        # samples from dead threads whose ids were reused (bounded fold)
        self._folded: List[float] = []
        self._tls = threading.local()

    def _local(self) -> _Reservoir:
        r = getattr(self._tls, "res", None)
        if r is None:
            r = _Reservoir()
            self._tls.res = r
            tid = threading.get_ident()
            with self._lock:
                stale = self._reservoirs.get(tid)
                if stale is not None:
                    self._folded.extend(stale.samples)
                    del self._folded[:-_RESERVOIR_SIZE * 4]
                self._reservoirs[tid] = r
        return r

    def add(self, v: float):
        self._local().add(v)

    __lshift__ = lambda self, v: (self.add(v), self)[1]

    def merged_samples(self) -> List[float]:
        with self._lock:
            rs = list(self._reservoirs.values())
            out: List[float] = list(self._folded)
        for r in rs:
            out.extend(r.samples)
        return out

    @staticmethod
    def _pick(sorted_samples: List[float], ratio: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(len(sorted_samples) - 1, int(ratio * len(sorted_samples)))
        return sorted_samples[idx]

    def get_percentile(self, ratio: float) -> float:
        """ratio in [0,1], e.g. 0.99 for p99."""
        return self._pick(sorted(self.merged_samples()), ratio)

    def get_value(self):
        s = sorted(self.merged_samples())  # merge+sort once for all quantiles
        return {
            "p50": self._pick(s, 0.5),
            "p90": self._pick(s, 0.9),
            "p99": self._pick(s, 0.99),
            "p999": self._pick(s, 0.999),
        }

    def reset(self):
        with self._lock:
            rs = list(self._reservoirs.values())
            out: List[float] = self._folded
            self._folded = []
        for r in rs:
            out.extend(r.samples)
            r.samples = []
            r.num_added = 0
        return out
