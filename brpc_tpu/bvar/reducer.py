"""Reducer family: Adder/Maxer/Miner/IntRecorder (bvar/reducer.h).

The reference's write path touches only a per-thread agent (AgentGroup,
bvar/detail/agent_group.h:50); reads combine all agents. We keep exactly
that shape: each thread lazily registers an agent object holding a plain
Python number — mutating it is GIL-atomic-enough because only the owning
thread writes it; readers sum/combine a snapshot of agents plus the values
"folded" from dead threads.
"""

from __future__ import annotations

import threading
from typing import Callable

from brpc_tpu.bvar.variable import Variable


class _Agent:
    __slots__ = ("value", "count", "__weakref__")

    def __init__(self, identity):
        self.value = identity
        self.count = 0


class _ReducerBase(Variable):
    def __init__(self, identity, op: Callable):
        super().__init__()
        self._identity = identity
        self._op = op
        self._lock = threading.Lock()
        # strong refs keyed by thread id: a dead thread's final contribution
        # stays readable (an Adder must not forget a dead thread's counts);
        # if an id is reused, the stale agent folds into _folded first
        self._agents: dict = {}
        self._folded = identity
        self._tls = threading.local()

    def _agent(self) -> _Agent:
        ag = getattr(self._tls, "agent", None)
        if ag is None:
            ag = _Agent(self._identity)
            self._tls.agent = ag
            tid = threading.get_ident()
            with self._lock:
                stale = self._agents.get(tid)
                if stale is not None:
                    self._folded = self._op(self._folded, stale.value)
                self._agents[tid] = ag
        return ag

    def get_value(self):
        with self._lock:
            agents = list(self._agents.values())
            val = self._folded
        for ag in agents:
            val = self._op(val, ag.value)
        return val

    # which sampling mode Window uses for this reducer (window.py):
    # "cumulative" = snapshot get_value and subtract; "delta" = reset per tick
    SERIES_MODE = "delta"

    def reset(self):
        """Combine-and-clear. NOTE: clearing ag.value races with the owning
        thread's unlocked read-modify-write; subclasses with subtractable
        values (Adder/IntRecorder) override this with an exact offset-based
        version — this base version is only for Maxer/Miner, where a racing
        update merely lands in the next interval."""
        with self._lock:
            agents = list(self._agents.values())
            val = self._folded
            self._folded = self._identity
            for ag in agents:
                val = self._op(val, ag.value)
                ag.value = self._identity
        return val


class Adder(_ReducerBase):
    """bvar::Adder — contention-free counter (reducer.h:224)."""

    SERIES_MODE = "cumulative"

    def __init__(self, value=0):
        super().__init__(value, lambda a, b: a + b)
        self._reset_offset = value

    def add(self, n=1):
        self._agent().value += n

    def __lshift__(self, n):
        self.add(n)
        return self

    def _raw_total(self):
        with self._lock:
            agents = list(self._agents.values())
            val = self._folded
        for ag in agents:
            val = self._op(val, ag.value)
        return val

    def get_value(self):
        return self._raw_total() - self._reset_offset

    def reset(self):
        """Exact combine-since-last-reset: subtract a remembered offset
        instead of clearing agent values (which would race with the owning
        threads' unlocked `value += n`)."""
        with self._lock:
            agents = list(self._agents.values())
            val = self._folded
            for ag in agents:
                val = self._op(val, ag.value)
            delta = val - self._reset_offset
            self._reset_offset = val
        return delta


class Maxer(_ReducerBase):
    def __init__(self):
        super().__init__(None, lambda a, b: b if a is None else (a if b is None else max(a, b)))

    def update(self, v):
        ag = self._agent()
        if ag.value is None or v > ag.value:
            ag.value = v

    __lshift__ = lambda self, v: (self.update(v), self)[1]


class Miner(_ReducerBase):
    def __init__(self):
        super().__init__(None, lambda a, b: b if a is None else (a if b is None else min(a, b)))

    def update(self, v):
        ag = self._agent()
        if ag.value is None or v < ag.value:
            ag.value = v

    __lshift__ = lambda self, v: (self.update(v), self)[1]


class IntRecorder(Variable):
    """Average of recorded ints; sum+count per thread agent (recorder.h:84)."""

    def __init__(self):
        super().__init__()
        self._sum = Adder(0)
        self._count = Adder(0)

    def record(self, v: int, times: int = 1):
        self._sum.add(v * times)
        self._count.add(times)

    __lshift__ = lambda self, v: (self.record(v), self)[1]

    @property
    def sum(self) -> int:
        return self._sum.get_value()

    @property
    def count(self) -> int:
        return self._count.get_value()

    def average(self) -> float:
        c = self.count
        return (self.sum / c) if c else 0.0

    def get_value(self):
        return self.average()

    def reset(self):
        s = self._sum.reset()
        c = self._count.reset()
        return (s, c)


class PassiveStatus(Variable):
    """Callback-valued variable (bvar/passive_status.h:42)."""

    def __init__(self, fn: Callable[[], object]):
        super().__init__()
        self._fn = fn

    def get_value(self):
        return self._fn()


class Status(Variable):
    """Set-valued variable (bvar/status.h:44)."""

    def __init__(self, value=None):
        super().__init__()
        self._value = value

    def set_value(self, v):
        self._value = v

    def get_value(self):
        return self._value
