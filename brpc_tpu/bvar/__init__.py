"""Metrics: thread-local-combining counters, windows, percentiles.

TPU-native re-design of the reference's ``src/bvar`` (SURVEY.md §2.3).
Write paths touch only per-thread agents (no contention); reads combine.
"""

from brpc_tpu.bvar.variable import Variable, expose, dump_exposed, describe_exposed, unexpose_all
from brpc_tpu.bvar.reducer import Adder, Maxer, Miner, IntRecorder, PassiveStatus, Status
from brpc_tpu.bvar.percentile import Percentile
from brpc_tpu.bvar.window import Window, PerSecond, Sampler, global_sampler
from brpc_tpu.bvar.series import (SeriesCollector, declare_series_kind,
                                  ensure_series, global_series,
                                  series_enabled, sparkline)
from brpc_tpu.bvar.anomaly import AnomalyWatchdog, global_watchdog
from brpc_tpu.bvar.latency_recorder import LatencyRecorder
from brpc_tpu.bvar.prometheus import dump_prometheus
from brpc_tpu.bvar.multi_dimension import MultiDimension
from brpc_tpu.bvar.default_variables import expose_default_variables
from brpc_tpu.bvar.gflag import FlagVar, expose_flag, expose_all_flags

__all__ = [
    "Variable", "expose", "dump_exposed", "describe_exposed", "unexpose_all",
    "Adder", "Maxer", "Miner", "IntRecorder", "PassiveStatus", "Status",
    "Percentile", "Window", "PerSecond", "Sampler", "global_sampler",
    "SeriesCollector", "declare_series_kind", "ensure_series",
    "global_series", "series_enabled", "sparkline",
    "AnomalyWatchdog", "global_watchdog",
    "LatencyRecorder", "dump_prometheus", "MultiDimension",
    "expose_default_variables", "FlagVar", "expose_flag", "expose_all_flags",
]
