"""Default process/system variables (bvar/default_variables.cpp): cpu,
rss, fds, threads, io, uptime — sampled lazily from /proc with a short
cache so a /vars scrape doesn't hammer procfs."""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from brpc_tpu.bvar.reducer import PassiveStatus

_CACHE_S = 0.5
_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_start_time = time.time()


class _ProcSampler:
    """One /proc read per cache window serving all derived vars."""

    def __init__(self):
        self._ts = 0.0
        self._stat: Dict[str, float] = {}
        self._last_cpu: Optional[tuple] = None  # (wall, user+sys seconds)
        self._cpu_pct = 0.0

    def sample(self) -> Dict[str, float]:
        now = time.monotonic()
        if now - self._ts < _CACHE_S and self._stat:
            return self._stat
        out: Dict[str, float] = {}
        try:
            with open("/proc/self/stat") as f:
                parts = f.read().split()
            # fields (1-indexed): 14 utime, 15 stime, 20 num_threads, 23 vsize
            utime, stime = int(parts[13]), int(parts[14])
            out["threads"] = int(parts[19])
            out["vsize_bytes"] = int(parts[22])
            out["rss_bytes"] = int(parts[23]) * _PAGE
            cpu_s = (utime + stime) / _CLK_TCK
            if self._last_cpu is not None:
                dwall = now - self._last_cpu[0]
                dcpu = cpu_s - self._last_cpu[1]
                if dwall > 0:
                    self._cpu_pct = max(0.0, dcpu / dwall)
            self._last_cpu = (now, cpu_s)
            out["cpu_usage"] = round(self._cpu_pct, 4)
            out["cpu_seconds_total"] = round(cpu_s, 3)
        except (OSError, IndexError, ValueError):
            pass
        try:
            out["fd_count"] = len(os.listdir("/proc/self/fd"))
        except OSError:
            out["fd_count"] = -1
        try:
            with open("/proc/self/io") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    if k in ("read_bytes", "write_bytes"):
                        out[f"io_{k}"] = int(v)
        except (OSError, ValueError):
            pass
        try:
            out["loadavg_1m"] = os.getloadavg()[0]
        except OSError:
            pass
        out["uptime_seconds"] = round(time.time() - _start_time, 1)
        self._ts = now
        self._stat = out
        return out


_sampler = _ProcSampler()


def _getter(key: str):
    return lambda: _sampler.sample().get(key, 0)


def expose_default_variables() -> None:
    """Idempotent: register the process_* vars (default_variables.cpp
    exposes at global init; here the first Server.start does it). Always
    (re)exposes — a flag would go stale after unexpose_all()."""
    from brpc_tpu.bvar.variable import dump_exposed_variables
    if any(n == "process_pid" for n, _ in dump_exposed_variables("process_")):
        return
    for key, name in [
        ("cpu_usage", "process_cpu_usage"),
        ("cpu_seconds_total", "process_cpu_seconds_total"),
        ("rss_bytes", "process_memory_resident"),
        ("vsize_bytes", "process_memory_virtual"),
        ("fd_count", "process_fd_count"),
        ("threads", "process_thread_count"),
        ("io_read_bytes", "process_io_read_bytes"),
        ("io_write_bytes", "process_io_write_bytes"),
        ("loadavg_1m", "system_loadavg_1m"),
        ("uptime_seconds", "process_uptime_seconds"),
    ]:
        PassiveStatus(_getter(key)).expose(name)
    PassiveStatus(lambda: os.getpid()).expose("process_pid")
    # IOBuf block-pool health (butil/iobuf.py BlockPool): the hit ratio
    # is THE "are blocks recycling or reallocating per call" signal the
    # hot-path overhaul is accountable for; bytes shows what the pool
    # currently pins
    from brpc_tpu.butil.iobuf import pool as _iobuf_pool
    PassiveStatus(lambda: round(_iobuf_pool.hit_ratio(), 4)).expose(
        "iobuf_pool_hit_ratio")
    PassiveStatus(_iobuf_pool.cached_bytes).expose("iobuf_pool_bytes")
