"""Flag <-> bvar bridge (bvar/gflag.{h,cpp}): expose a runtime flag's
current value as a Variable so it shows up in /vars and windowed dumps,
staying live as /flags mutates it."""

from __future__ import annotations

from typing import Optional

from brpc_tpu.butil.flags import flag, list_flags
from brpc_tpu.bvar.variable import Variable


class FlagVar(Variable):
    def __init__(self, flag_name: str):
        super().__init__()
        self._flag_name = flag_name
        flag(flag_name)  # raises now if undefined, not at dump time

    @property
    def flag_name(self) -> str:
        return self._flag_name

    def get_value(self):
        return flag(self._flag_name)


def expose_flag(flag_name: str, bvar_name: Optional[str] = None) -> FlagVar:
    return FlagVar(flag_name).expose(bvar_name or f"flag_{flag_name}")


def expose_all_flags(prefix: str = "flag_") -> int:
    """Expose every defined flag as ``<prefix><name>``; returns count."""
    n = 0
    for name, _v, _d, _h in list_flags():
        expose_flag(name, prefix + name)
        n += 1
    return n
