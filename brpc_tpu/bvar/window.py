"""Window / PerSecond over a reducer, fed by a background Sampler
(bvar/window.h:174,197; bvar/detail/sampler.h:45).

The Sampler thread ticks once per second, snapshotting every registered
windowed variable into a ring of per-second samples. Windows read the last
N samples. Two sampling modes, chosen by the reducer's SERIES_MODE (the
reference's ReducerSampler makes the same split):

  cumulative — subtractable reducers (Adder): store get_value snapshots,
               window value = newest - oldest.
  delta      — op-combined reducers (Maxer/Miner): store per-tick
               reducer.reset() values, window value = op over last N ticks
               (a plain subtraction of cumulative maxima would be
               meaningless).

Tests can drive ``take_sample()`` manually instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple

from brpc_tpu.bvar.variable import Variable
# the trend-ring engine rides THIS module's tick thread: bound at
# module load, never inside take_sample (sampler-thread code must not
# lazily import — the PR 8 fd-hazard rule). No cycle: series imports
# only variable/flags/anomaly.
from brpc_tpu.bvar.series import series_sample_tick

_MAX_WINDOW = 120


class _SeriesSampler:
    """Keeps per-second samples of one reducer."""

    def __init__(self, reducer):
        self.reducer = reducer
        self.mode = getattr(reducer, "SERIES_MODE", "cumulative")
        self.samples: Deque[Tuple[float, object]] = deque(maxlen=_MAX_WINDOW + 1)

    def take_sample(self, now: float):
        if self.mode == "delta":
            self.samples.append((now, self.reducer.reset()))
        else:
            self.samples.append((now, self.reducer.get_value()))


class Sampler:
    """One background thread samples all windowed vars 1/s
    (bvar/detail/sampler.cpp)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: list = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def register(self, series: _SeriesSampler):
        with self._lock:
            self._series.append(series)
        self._ensure_thread()

    def take_sample(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            series = list(self._series)
        for s in series:
            s.take_sample(now)
        if self is global_sampler:
            # multi-resolution trend rings + the anomaly watchdog ride
            # the same 1/s stamp (bvar/series.py; buckets stamp on the
            # wall clock, not this monotonic now) — only the GLOBAL
            # sampler: private test samplers drive synthetic clocks
            # that must not pollute the process rings
            series_sample_tick()

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="bvar_sampler", daemon=True)
            self._thread.start()

    def _run(self):
        while not self._stop.wait(1.0):
            self.take_sample()

    def stop(self):
        self._stop.set()


global_sampler = Sampler()


def _postfork_reset() -> None:
    """Fork hygiene: the sampler thread exists only in the parent and
    its lock may be held by that dead thread (fork mid-sample). Fresh
    lock, and restart the tick thread iff anything is registered —
    inherited Windows keep sampling in the child."""
    global_sampler._lock = threading.Lock()
    global_sampler._stop = threading.Event()
    global_sampler._thread = None
    if global_sampler._series:
        global_sampler._ensure_thread()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singleton it resets)

_postfork.register("bvar.window", _postfork_reset)


class Window(Variable):
    """Value accumulated over the last ``window_size`` seconds."""

    def __init__(self, reducer, window_size: int = 10, sampler: Optional[Sampler] = None):
        super().__init__()
        self._reducer = reducer
        self.window_size = min(window_size, _MAX_WINDOW)
        self._series = _SeriesSampler(reducer)
        (sampler or global_sampler).register(self._series)

    def _window_samples(self):
        s = self._series.samples
        if not s:
            return []
        return list(s)[-(self.window_size + 1):]

    def get_value(self):
        samples = self._window_samples()
        if self._series.mode == "delta":
            # combine the last window_size per-tick deltas with the op
            ticks = [v for (_, v) in samples[-self.window_size:]]
            op = self._reducer._op
            val = None
            for v in ticks:
                val = v if val is None else op(val, v)
            return val
        if len(samples) < 2:
            # window not warm yet: report the total so far
            return self._reducer.get_value()
        (t0, v0), (t1, v1) = samples[0], samples[-1]
        try:
            return v1 - v0
        except TypeError:
            return v1

    def get_span_seconds(self) -> float:
        samples = self._window_samples()
        if len(samples) < 2:
            return 0.0
        return samples[-1][0] - samples[0][0]


class PerSecond(Window):
    """Windowed delta divided by elapsed seconds (qps etc.). Only
    meaningful over cumulative-mode reducers (Adder)."""

    def get_value(self):
        samples = self._window_samples()
        if len(samples) < 2:
            return 0.0
        (t0, v0), (t1, v1) = samples[0], samples[-1]
        dt = t1 - t0
        if dt <= 0:
            return 0.0
        if self._series.mode == "delta":
            total = sum(v for (_, v) in samples[1:] if v is not None)
            return total / dt
        return (v1 - v0) / dt
