"""Thrift framed server + client (example/thrift_extension_c++)."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.protocol import thrift as th
from brpc_tpu.rpc import Server, ServerOptions


def main(addr: str = "tcp://127.0.0.1:8019") -> None:
    svc = th.ThriftService()

    @svc.method("Echo")
    def echo(sock, args):
        # args: {1: TVal(T_STRING, data)} — the conventional request slot
        return {0: th.TVal(th.T_STRING, args[1].value)}

    server = Server(ServerOptions(thrift_service=svc))
    ep = server.start(addr)
    print(f"thrift server at {ep}")

    client = th.ThriftClient(ep)
    out = client.call("Echo", {1: th.TVal(th.T_STRING, b"hello thrift")})
    print("Echo ->", out[0].value)
    client.close()
    server.run_until_asked_to_quit()


if __name__ == "__main__":
    main(*sys.argv[1:])
