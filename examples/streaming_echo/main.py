"""Bidirectional streaming echo (example/streaming_echo_c++) over the
ici:// device-fabric transport, with per-frame latency percentiles.

Streams ride the same connection as ordinary RPCs (stream ids
piggyback on the Open call), so this exercises credit-based stream
flow control on top of the ici framing."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu import fiber
from brpc_tpu.bvar.latency_recorder import LatencyRecorder
from brpc_tpu.rpc import Channel, Server, ServerOptions, Service
from brpc_tpu.rpc.stream import StreamOptions, stream_accept


def main(n_frames: int = 20, address: str = "") -> None:
    n_frames = int(n_frames)
    server = None
    if not address:
        server = Server(ServerOptions(enable_builtin_services=False))
        svc = Service("StreamEcho")

        @svc.method()
        def Open(cntl, request):
            def on_received(stream, msg):
                stream.write_nowait(b"echo:" + msg.payload.to_bytes())
            s = stream_accept(cntl, StreamOptions(on_received=on_received))
            if s is not None:
                # handler-owned stream: self-close on the client's close
                s.on_close(lambda st: st.close())
            return b"accepted"

        server.add_service(svc)
        ep = server.start("ici://127.0.0.1:0#device=0")
        address = f"ici://127.0.0.1:{ep.port}"

    got = []
    rec = LatencyRecorder()
    sent_ns = {}
    ch = Channel(address)
    def on_echo(s, m):
        body = m.payload.to_bytes()
        got.append(body)
        idx = body.rsplit(b"-", 1)[-1]
        t0 = sent_ns.pop(idx, None)
        if t0 is not None:
            rec.record((time.perf_counter_ns() - t0) / 1e3)

    cntl = ch.call_sync("StreamEcho", "Open", b"",
                        stream_options=StreamOptions(on_received=on_echo))
    stream = cntl.stream

    async def producer():
        for i in range(n_frames):
            sent_ns[str(i).encode()] = time.perf_counter_ns()
            ok = await stream.write(f"frame-{i}".encode())
            assert ok, "stream write failed"

    f = fiber.spawn(producer)
    f.join(10)
    deadline = time.monotonic() + 5
    while len(got) < n_frames and time.monotonic() < deadline:
        time.sleep(0.01)
    print(f"sent {n_frames} frames, got {len(got)} echoes; "
          f"first={got[0]!r} last={got[-1]!r}")
    print(f"frame rtt: p50={rec.latency_percentile(0.5):.1f}us "
          f"p99={rec.latency_percentile(0.99):.1f}us")
    stream.close()
    ch.close()
    if server is not None:
        server.stop()
        server.join(2)


if __name__ == "__main__":
    main(*sys.argv[1:])
