"""Bidirectional streaming echo (example/streaming_echo_c++)."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu import fiber
from brpc_tpu.rpc import Channel, Server, ServerOptions, Service
from brpc_tpu.rpc.stream import StreamOptions, stream_accept


def main(n_frames: int = 20) -> None:
    n_frames = int(n_frames)
    server = Server(ServerOptions(enable_builtin_services=False))
    svc = Service("StreamEcho")

    @svc.method()
    def Open(cntl, request):
        def on_received(stream, msg):
            stream.write_nowait(b"echo:" + msg.payload.to_bytes())
        stream_accept(cntl, StreamOptions(on_received=on_received))
        return b"accepted"

    server.add_service(svc)
    ep = server.start("mem://streaming-echo")

    got = []
    ch = Channel(str(ep))
    cntl = ch.call_sync("StreamEcho", "Open", b"", stream_options=StreamOptions(
        on_received=lambda s, m: got.append(m.payload.to_bytes())))
    stream = cntl.stream

    async def producer():
        for i in range(n_frames):
            ok = await stream.write(f"frame-{i}".encode())
            assert ok, "stream write failed"

    f = fiber.spawn(producer)
    f.join(10)
    deadline = time.monotonic() + 5
    while len(got) < n_frames and time.monotonic() < deadline:
        time.sleep(0.01)
    print(f"sent {n_frames} frames, got {len(got)} echoes; "
          f"first={got[0]!r} last={got[-1]!r}")
    stream.close()
    server.stop()
    server.join(2)


if __name__ == "__main__":
    main(*sys.argv[1:])
