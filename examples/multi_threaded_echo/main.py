"""Fiber-concurrency echo stress (example/multi_threaded_echo_c++):
N fibers hammer one server over mem:// loopback, reporting qps + latency
percentiles from a LatencyRecorder."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu import fiber
from brpc_tpu.bvar import LatencyRecorder, global_sampler
from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions, Service


def main(n_fibers: int = 16, seconds: float = 3.0) -> None:
    n_fibers, seconds = int(n_fibers), float(seconds)
    server = Server(ServerOptions(enable_builtin_services=False))
    svc = Service("EchoService")
    svc.register_method("Echo", lambda cntl, req: req)
    server.add_service(svc)
    ep = server.start("mem://mt-echo")

    lat = LatencyRecorder()
    ch = Channel(str(ep), ChannelOptions(timeout_ms=5000))
    stop_at = time.monotonic() + seconds
    counts = [0] * n_fibers

    async def worker(idx: int):
        while time.monotonic() < stop_at:
            t0 = time.perf_counter_ns()
            cntl = await ch.call_async("EchoService", "Echo", b"ping")
            if not cntl.failed():
                lat.record((time.perf_counter_ns() - t0) / 1e3)
                counts[idx] += 1

    fibers = [fiber.spawn(worker, i) for i in range(n_fibers)]
    for f in fibers:
        f.join(seconds + 30)
    total = sum(counts)
    global_sampler.take_sample()
    print(f"fibers={n_fibers} total={total} qps={total/seconds:.0f} "
          f"avg={lat.latency():.0f}us p99={lat.latency_percentile(0.99):.0f}us "
          f"max={lat.max_latency():.0f}us")
    server.stop()
    server.join(2)


if __name__ == "__main__":
    main(*sys.argv[1:])
