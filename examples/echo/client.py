"""Sync echo client (example/echo_c++/client.cpp)."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import Channel, ChannelOptions


def main(addr: str = "tcp://127.0.0.1:8000", n: int = 10) -> None:
    ch = Channel(addr, ChannelOptions(timeout_ms=1000))
    for i in range(int(n)):
        cntl = ch.call_sync("EchoService", "Echo", f"hello {i}".encode())
        if cntl.failed():
            print(f"call failed: {cntl.error_text}")
        else:
            print(f"{cntl.response_payload.to_bytes().decode()}  "
                  f"latency={cntl.latency_us()}us")
        time.sleep(0.2)


if __name__ == "__main__":
    main(*sys.argv[1:])
