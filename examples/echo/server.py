"""Sync echo server (example/echo_c++/server.cpp). Serves tpu_std AND
http on one port — try `curl localhost:8000/status`."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import Server, Service


def main(addr: str = "tcp://127.0.0.1:8000") -> None:
    server = Server()
    svc = Service("EchoService")

    @svc.method()
    def Echo(cntl, request):
        return request

    server.add_service(svc)
    ep = server.start(addr)
    print(f"EchoServer listening at {ep} (curl http://{ep.host}:{ep.port}/status)")
    server.run_until_asked_to_quit()


if __name__ == "__main__":
    main(*sys.argv[1:])
