"""tpu_performance: the 4B-4MB payload sweep (example/rdma_performance
rebuilt for tpu:// — BASELINE.md's north-star config). Reports per-size
throughput and latency over the device lane."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])


def main(iters: int = 50) -> None:
    import jax
    import jax.numpy as jnp

    from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions, Service

    iters = int(iters)
    server = Server(ServerOptions(enable_builtin_services=False))
    svc = Service("Perf")

    @svc.method()
    def Echo(cntl, request):
        cntl.response_device_arrays = cntl.request_device_arrays
        return b""

    server.add_service(svc)
    ep = server.start("tpu://perf:1#device=0")
    ch = Channel(str(ep), ChannelOptions(timeout_ms=60000))

    print(f"{'size':>10} {'avg_us':>10} {'GB/s':>8}")
    size = 4
    while size <= 4 * 1024 * 1024:
        n = max(1, size // 4)
        payload = jax.block_until_ready(jnp.ones((n,), jnp.float32))
        for _ in range(5):
            ch.call_sync("Perf", "Echo", b"", request_device_arrays=[payload])
        t0 = time.perf_counter()
        for _ in range(iters):
            cntl = ch.call_sync("Perf", "Echo", b"",
                                request_device_arrays=[payload])
            assert not cntl.failed(), cntl.error_text
        dt = time.perf_counter() - t0
        gbps = iters * n * 4 * 2 / dt / 1e9
        print(f"{n*4:>10} {dt/iters*1e6:>10.1f} {gbps:>8.3f}")
        size *= 4
    server.stop()
    server.join(2)


if __name__ == "__main__":
    main(*sys.argv[1:])
