"""tpu_performance: the 4B-4MB payload sweep (example/rdma_performance
rebuilt for the device fabric — BASELINE.md's north-star config).

Runs over ici:// — the PjRt pull-DMA data plane (the RDMA slot) — and
reports per-size throughput plus p50/p99 latency from a
bvar.LatencyRecorder, the same runtime shape as
example/rdma_performance/client.cpp:261 (QPS + bvar percentiles).

Usage: main.py [iters] [address]
  address defaults to an in-process ici:// loopback on 127.0.0.1; point
  it at another host's ici_echo server for a true two-process run.
"""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.butil.jax_env import apply_jax_platforms_env

apply_jax_platforms_env()  # env choice beats the axon plugin's override


def main(iters: int = 30, address: str = "") -> None:
    import jax
    import jax.numpy as jnp

    from brpc_tpu.bvar.latency_recorder import LatencyRecorder
    from brpc_tpu.rpc import (Channel, ChannelOptions, Server, ServerOptions,
                              Service)

    iters = int(iters)
    server = None
    if not address:
        server = Server(ServerOptions(enable_builtin_services=False))
        svc = Service("Perf")

        @svc.method()
        def Echo(cntl, request):
            cntl.response_device_arrays = cntl.request_device_arrays
            return b""

        server.add_service(svc)
        ep = server.start("ici://127.0.0.1:0#device=0")
        address = f"ici://127.0.0.1:{ep.port}#reply_device=0"

    ch = Channel(address, ChannelOptions(timeout_ms=60000))

    print(f"{'size':>10} {'avg_us':>10} {'p50_us':>10} {'p99_us':>10} "
          f"{'GB/s':>8}")
    size = 4
    lane = None
    while size <= 4 * 1024 * 1024:
        n = max(1, size // 4)
        payload = jax.block_until_ready(jnp.ones((n,), jnp.float32))
        for _ in range(3):
            cntl = ch.call_sync("Perf", "Echo", b"",
                                request_device_arrays=[payload])
            assert not cntl.failed(), cntl.error_text
        if lane is None:
            lane = ch._get_socket().conn.lane_kind
        rec = LatencyRecorder()
        t0 = time.perf_counter()
        for _ in range(iters):
            c0 = time.perf_counter_ns()
            cntl = ch.call_sync("Perf", "Echo", b"",
                                request_device_arrays=[payload])
            assert not cntl.failed(), cntl.error_text
            rec.record((time.perf_counter_ns() - c0) / 1e3)
        dt = time.perf_counter() - t0
        gbps = iters * n * 4 * 2 / dt / 1e9
        print(f"{n*4:>10} {rec.latency():>10.1f} "
              f"{rec.latency_percentile(0.5):>10.1f} "
              f"{rec.latency_percentile(0.99):>10.1f} {gbps:>8.3f}")
        size *= 4
    print(f"lane: {lane}")
    ch.close()
    if server is not None:
        server.stop()
        server.join(2)


if __name__ == "__main__":
    main(*sys.argv[1:])
