"""Authenticator + Interceptor example (example/auth_c++): credential
verification with per-connection caching and a per-request admission
gate."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import (
    AuthContext, AuthError, Authenticator, Channel, ChannelOptions,
    InterceptorError, Server, ServerOptions, Service,
)
from brpc_tpu.rpc import errno_codes as berr


class ApiKeyAuth(Authenticator):
    KEYS = {"key-alice": "alice", "key-bob": "bob"}

    def __init__(self, key=""):
        self.key = key

    def generate_credential(self):
        return self.key

    def verify_credential(self, credential, remote_side):
        user = self.KEYS.get(credential)
        if user is None:
            raise AuthError("unknown api key")
        return AuthContext(user=user)


def interceptor(cntl):
    if cntl.method_name == "Admin" and \
            (cntl.auth_context is None or cntl.auth_context.user != "alice"):
        raise InterceptorError(berr.EPERM, "Admin is alice-only")


def main() -> None:
    server = Server(ServerOptions(auth=ApiKeyAuth(), interceptor=interceptor))
    svc = Service("Demo")

    @svc.method()
    def Hello(cntl, request):
        return f"hello {cntl.auth_context.user}".encode()

    @svc.method()
    def Admin(cntl, request):
        return b"secret admin data"

    server.add_service(svc)
    ep = server.start("tcp://127.0.0.1:0")

    for key in ("key-alice", "key-bob", "key-eve"):
        ch = Channel(ep, ChannelOptions(auth=ApiKeyAuth(key)))
        for method in ("Hello", "Admin"):
            cntl = ch.call_sync("Demo", method, b"")
            outcome = (cntl.response_payload.to_bytes().decode()
                       if not cntl.failed()
                       else f"DENIED [{cntl.error_code}] {cntl.error_text}")
            print(f"{key:10s} {method:6s} -> {outcome}")
        ch.close()
    server.stop(); server.join()


if __name__ == "__main__":
    main()
