"""Cross-host device-RPC server over ici:// — the PjRt pull-DMA data
plane (the RDMA slot; falls back to the host-staged lane when either
side lacks a transfer server). Run this on one host, client.py on
another (or another process on the same host)."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import Server, Service


def main(addr: str = "ici://127.0.0.1:8750#device=0") -> None:
    server = Server()
    svc = Service("TensorService")

    @svc.method()
    def Scale(cntl, request):
        factor = float(bytes(request) or b"2")
        # the arrays already live on THIS process's device (the lane
        # pulled them); scale on-device, no host round-trip
        cntl.response_device_arrays = [
            a * factor for a in cntl.request_device_arrays]
        return b"scaled"

    server.add_service(svc)
    ep = server.start(addr)
    print(f"tensor server at {ep}", flush=True)
    server.run_until_asked_to_quit()


if __name__ == "__main__":
    main(*sys.argv[1:])
