"""Cross-host device-RPC server (tpud:// — the DCN path): run this on
one host, client.py on another (or another process on the same host)."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

import numpy as np

from brpc_tpu.rpc import Server, Service


def main(addr: str = "tpud://127.0.0.1:8750") -> None:
    server = Server()
    svc = Service("TensorService")

    @svc.method()
    def Scale(cntl, request):
        factor = float(bytes(request) or b"2")
        cntl.response_device_arrays = [
            np.asarray(a) * factor for a in cntl.request_device_arrays]
        return b"scaled"

    server.add_service(svc)
    ep = server.start(addr)
    print(f"tensor server at {ep}", flush=True)
    server.run_until_asked_to_quit()


if __name__ == "__main__":
    main(*sys.argv[1:])
