"""Cross-host device-RPC client: arrays travel the ici:// device lane
(receiver-driven PjRt pull DMA when both sides have a transfer server;
check the printed lane_kind)."""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

import numpy as np

from brpc_tpu.rpc import Channel, ChannelOptions


def main(addr: str = "ici://127.0.0.1:8750#reply_device=0") -> None:
    ch = Channel(addr, ChannelOptions(timeout_ms=30000))
    x = np.arange(8, dtype=np.float32)
    cntl = ch.call_sync("TensorService", "Scale", b"3",
                        request_device_arrays=[x])
    assert not cntl.failed(), cntl.error_text
    out = np.asarray(cntl.response_device_arrays[0])
    print("sent     ", x)
    print("received ", out)
    print("lane     ", ch._socket.conn.lane_kind)
    print("peer info", ch._socket.conn.peer_info)
    ch.close()


if __name__ == "__main__":
    main(*sys.argv[1:])
