"""Inference serving demo: a continuous-batching GenerateService with
tokens streamed back as they decode.

The server registers the serving lane (``add_generate_service``) — a
deterministic toy decoder whose decode steps run ON the fiber workers
through the WorkerModule hook — and the client opens a streaming
Generate call, printing each token the moment its frame arrives
(time-to-first-token is the first decode step, not batch completion).

Run it::

    python examples/inference_serving/main.py            # in-process
    python examples/inference_serving/main.py '' 64      # 64 tokens
    python examples/inference_serving/main.py tcp://host:port  # client

Server-only (e.g. to serve several clients, sharded across 2 worker
processes with one model replica each)::

    python -c "import sys; sys.argv=['x','--serve']; \
               exec(open('examples/inference_serving/main.py').read())"
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import Channel, Server
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.stream import StreamOptions


def main(address: str = "", max_tokens: int = 32,
         prompt: str = "the quick brown fox") -> None:
    max_tokens = int(max_tokens)
    server = None
    if not address:
        from brpc_tpu.serving import add_generate_service
        server = Server()
        add_generate_service(server)
        ep = server.start("tcp://127.0.0.1:0")
        address = f"tcp://127.0.0.1:{ep.port}"
        print(f"serving on {address} (builtin console: "
              f"http://127.0.0.1:{ep.port}/serving)")

    ch = Channel(address)
    state = {"t0": 0.0, "ttft": None, "done": False, "n": 0}

    def on_frame(stream, msg):
        p = msg.payload.to_bytes()
        tag, rest = p[:1], p[1:]
        if tag == b"t":
            now = time.monotonic()
            if state["ttft"] is None:
                state["ttft"] = now - state["t0"]
            state["n"] += 1
            # print each token AS IT ARRIVES (byte-level vocab)
            sys.stdout.write(f"{rest[0]:3d} ")
            sys.stdout.flush()
        elif tag == b"d":
            doc = json.loads(rest.decode())
            print(f"\n[done: {doc['n']} tokens]")
            state["done"] = True
        elif tag == b"e":
            print(f"\n[failed: errno {rest.decode()}]")
            state["done"] = True

    cntl = Controller()
    cntl.timeout_ms = 60000
    state["t0"] = time.monotonic()
    cntl = ch.call_sync(
        "GenerateService", "Generate",
        json.dumps({"prompt": prompt, "max_tokens": max_tokens}).encode(),
        cntl=cntl, stream_options=StreamOptions(on_received=on_frame))
    assert not cntl.failed(), cntl.error_text
    print(f"prompt: {prompt!r} -> streaming {max_tokens} tokens:")

    deadline = time.monotonic() + 60
    while not state["done"] and time.monotonic() < deadline:
        time.sleep(0.01)
    total = time.monotonic() - state["t0"]
    print(f"ttft {state['ttft'] * 1e3:.1f}ms, "
          f"total {total * 1e3:.1f}ms, "
          f"{state['n'] / max(total, 1e-9):.0f} tokens/s")
    cntl.stream.close()
    ch.close()
    if server is not None:
        server.stop()
        server.join(2)


if __name__ == "__main__":
    if "--serve" in sys.argv:
        from brpc_tpu.serving import add_generate_service
        srv = Server()
        add_generate_service(srv)
        endpoint = srv.start("tcp://127.0.0.1:0", num_shards=2)
        print(f"serving (2 shards) on tcp://127.0.0.1:{endpoint.port}")
        srv.run_until_asked_to_quit()
    else:
        main(*sys.argv[1:])
