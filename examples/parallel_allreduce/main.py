"""ParallelChannel 8-shard allreduce (BASELINE.md's new combo-channel
bench), shown both ways:

  host path   — ParallelChannel fans one request out to 8 servers, each
                reduces its shard, the merger sums on the host
  device path — CollectiveChannel lowers the same dataflow to one SPMD
                psum over the mesh (the TPU-native answer)
"""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.butil.jax_env import apply_jax_platforms_env

apply_jax_platforms_env()  # env choice beats the axon plugin's override

import numpy as np


def main(n_shards: int = 8, dim: int = 1 << 16) -> None:
    n_shards, dim = int(n_shards), int(dim)

    # ---------------- host path: 8 real servers + ParallelChannel
    from brpc_tpu.rpc import (Channel, ParallelChannel, ResponseMerger, Server,
                              ServerOptions, Service, SubCall, CallMapper,
                              Controller)

    servers = []
    for i in range(n_shards):
        s = Server(ServerOptions(enable_builtin_services=False))
        svc = Service("Reduce")

        def Sum(cntl, request, _i=i):
            arr = np.frombuffer(request, dtype=np.float32)
            return np.array([arr.sum()], dtype=np.float32).tobytes()
        svc.register_method("Sum", Sum)
        s.add_service(svc)
        servers.append((s, s.start(f"mem://allreduce-{i}")))

    class ShardMapper(CallMapper):
        def map(self, i, n, service, method, request, cntl):
            shard = request[i * len(request) // n: (i + 1) * len(request) // n]
            return SubCall(service, method, shard)

    pch = ParallelChannel(call_mapper=ShardMapper())
    for _, ep in servers:
        pch.add_sub_channel(Channel(str(ep)))

    data = np.ones(dim, dtype=np.float32)
    t0 = time.perf_counter()
    cntl = pch.call_sync("Reduce", "Sum", data.tobytes())
    host_ms = (time.perf_counter() - t0) * 1e3
    total = sum(np.frombuffer(r, np.float32)[0] for r in cntl.sub_responses)
    print(f"host ParallelChannel: sum={total:.0f} (expect {dim}) in {host_ms:.2f}ms")
    for s, _ in servers:
        s.stop(); s.join(2)

    # ---------------- device path: one psum over the mesh
    import jax
    import jax.numpy as jnp
    from brpc_tpu.parallel import CollectiveChannel, make_rpc_mesh

    n_dev = min(n_shards, len(jax.devices()))
    mesh = make_rpc_mesh(n_replicas=1, n_shards=n_dev)
    cc = CollectiveChannel(mesh)
    x = jnp.ones((n_dev, dim // n_dev), jnp.float32)

    def shard_sum(s):  # one stable fn: cc.call caches the compilation by it
        return s.sum()[None]

    out = cc.call(shard_sum, x, merge="sum")  # warm compile
    t0 = time.perf_counter()
    out = jax.block_until_ready(cc.call(shard_sum, x, merge="sum"))
    dev_ms = (time.perf_counter() - t0) * 1e3
    print(f"device CollectiveChannel psum: sum={float(out[0]):.0f} in {dev_ms:.2f}ms "
          f"({n_dev} device(s))")


if __name__ == "__main__":
    main(*sys.argv[1:])
