"""Long-context sequence parallelism: ring attention + Ulysses all-to-all
over an 8-device mesh (the framework's 'large-payload streaming' analog
— SURVEY §5: blockwise neighbor exchange over the ring of ICI links).

Runs on a virtual CPU mesh anywhere:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context/main.py
"""

import sys

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.butil.jax_env import apply_jax_platforms_env

apply_jax_platforms_env()  # env choice beats the axon plugin's override


def main(seq: int = 2048) -> None:
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from brpc_tpu.ops.flash_attention import flash_attention
    from brpc_tpu.ops.ring_attention import ring_attention, ulysses_attention

    seq = int(seq)
    devs = jax.devices()
    n = len(devs)
    print(f"{n} device(s): {devs[0].platform}")
    mesh = Mesh(np.array(devs), ("shard",))

    heads, d = 8, 64
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (heads, seq, d)          # [heads, seq, head_dim]
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    ref = flash_attention(q, k, v, causal=True)

    for name, fn in (("ring", ring_attention), ("ulysses", ulysses_attention)):
        t0 = time.perf_counter()
        out = fn(mesh, q, k, v, causal=True)
        out = jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) * 1e3
        err = float(jnp.max(jnp.abs(out - ref)))
        print(f"{name:8s} seq={seq} sharded over {n}: "
              f"max|err|={err:.2e}  {dt:.1f}ms (incl. compile)")
        assert err < 2e-2, f"{name} diverged"
    print("long-context attention OK")


if __name__ == "__main__":
    main(*sys.argv[1:])
