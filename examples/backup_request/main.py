"""Backup request example (example/backup_request_c++): hedge a slow
replica with a second request; first response wins."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.rpc import (
    Channel, ChannelOptions, ClusterChannel, Server, Service, ServerOptions,
)


def start_server(delay_s):
    server = Server()
    svc = Service("EchoService")

    @svc.method()
    def Echo(cntl, request):
        time.sleep(delay_s)
        return f"served-after-{delay_s}s".encode()

    server.add_service(svc)
    ep = server.start("tcp://127.0.0.1:0")
    return server, ep


def main() -> None:
    slow, slow_ep = start_server(0.5)
    fast, fast_ep = start_server(0.0)
    ch = ClusterChannel(f"list://{slow_ep.host}:{slow_ep.port},"
                        f"{fast_ep.host}:{fast_ep.port}",
                        "rr", ChannelOptions(backup_request_ms=50,
                                             timeout_ms=3000))
    for i in range(4):
        t0 = time.monotonic()
        cntl = ch.call_sync("EchoService", "Echo", b"x")
        ms = (time.monotonic() - t0) * 1e3
        print(f"call {i}: {cntl.response_payload.to_bytes().decode():20s} "
              f"{ms:6.1f}ms  backup_used={cntl.used_backup}")
    ch.close()
    slow.stop(); fast.stop(); slow.join(); fast.join()


if __name__ == "__main__":
    main()
