"""Redis server + client example (example/redis_c++): an in-memory KV
served over RESP — redis-cli compatible — plus a pipelined client
driving it."""

import sys
import threading

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.protocol import redis
from brpc_tpu.rpc import Server, ServerOptions


def main(addr: str = "tcp://127.0.0.1:6380") -> None:
    svc = redis.RedisService()
    store, lock = {}, threading.Lock()

    @svc.command("SET")
    def set_(sock, args):
        with lock:
            store[args[1]] = args[2]
        return redis.RedisStatus("OK")

    @svc.command("GET")
    def get(sock, args):
        with lock:
            return store.get(args[1])

    @svc.command("DEL")
    def del_(sock, args):
        with lock:
            return sum(1 for k in args[1:] if store.pop(k, None) is not None)

    @svc.command("KEYS")
    def keys(sock, args):
        with lock:
            return sorted(store)

    server = Server(ServerOptions(redis_service=svc))
    ep = server.start(addr)
    print(f"redis server at {ep} — try: redis-cli -p {ep.port} set k v")

    client = redis.RedisClient(ep)
    print("SET greeting hello ->", client.execute("SET", "greeting", "hello"))
    print("GET greeting       ->", client.execute("GET", "greeting"))
    print("pipeline           ->", client.pipeline(
        [["SET", "a", "1"], ["SET", "b", "2"], ["KEYS"]]))
    client.close()
    server.run_until_asked_to_quit()


if __name__ == "__main__":
    main(*sys.argv[1:])
