"""RTMP live relay (example/rtmp_c++ / live_chat): one server, one
publisher pushing frames, one player receiving the relay. Point OBS or
`ffmpeg -f flv rtmp://127.0.0.1:1935/live/room` at it for real media."""

import sys
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.protocol import rtmp
from brpc_tpu.rpc import Server, ServerOptions


def main(addr: str = "tcp://127.0.0.1:1935") -> None:
    svc = rtmp.RtmpService()
    server = Server(ServerOptions(rtmp_service=svc))
    ep = server.start(addr)
    print(f"rtmp server at rtmp://{ep.host}:{ep.port}/live")

    pub = rtmp.RtmpClient(ep, app="live")
    pub.connect()
    psid = pub.create_stream()
    pub.publish(psid, "room")
    pub.send_metadata(psid, {"width": 1280.0, "height": 720.0})
    pub.send_video(psid, 0, b"\x17\x00<codec-config>")

    got = []
    sub = rtmp.RtmpClient(ep, app="live")
    sub.connect()
    sub.play(sub.create_stream(), "room", on_media=lambda m: got.append(m))

    for i in range(5):
        pub.send_video(psid, i * 40, b"\x27\x01" + bytes([i]) * 32)
    time.sleep(0.3)
    print(f"player received {len(got)} messages "
          f"({[m.msg_type for m in got]})")
    pub.close()
    sub.close()
    server.stop(); server.join()


if __name__ == "__main__":
    main(*sys.argv[1:])
