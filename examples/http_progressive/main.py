"""http_progressive: a server feeding an unbounded chunked download and
a framework HttpClient consuming it progressively — the
progressive_attachment + progressive_reader pair
(example/http_c++'s progressive modes in the reference).

Usage: main.py [total_mb]
"""

import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/examples", 1)[0])

from brpc_tpu.protocol.http_client import HttpClient
from brpc_tpu.rpc import Server, ServerOptions, Service


def main(total_mb: int = 4) -> None:
    server = Server(ServerOptions())
    svc = Service("FileService")
    chunk = b"\xab" * 65536

    @svc.method()
    def Download(cntl, request):
        pa = cntl.create_progressive_attachment("application/octet-stream")

        def feed():
            for _ in range(total_mb * 16):   # 16 x 64KB per MB
                if not pa.write(chunk):
                    return                   # client went away
            pa.close()

        threading.Thread(target=feed, daemon=True).start()

    server.add_service(svc)
    ep = server.start("tcp://127.0.0.1:0")

    cl = HttpClient(f"tcp://127.0.0.1:{ep.port}")
    got = [0]
    parts = [0]
    t0 = time.monotonic()

    def on_chunk(data: bytes) -> None:
        got[0] += len(data)
        parts[0] += 1

    status, headers, _ = cl.get("/FileService/Download", on_chunk=on_chunk,
                                timeout_s=60)
    dt = time.monotonic() - t0
    print(f"status={status} received={got[0] / 1e6:.1f}MB in "
          f"{parts[0]} parts, {got[0] / dt / 1e6:.0f} MB/s")
    assert status == 200 and got[0] == total_mb << 20
    cl.close()
    server.stop()
    server.join(2)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4)
